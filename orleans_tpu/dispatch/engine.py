"""The tick engine: coalesce VectorGrain invocations into batched kernels.

This replaces the reference's hot path — IncomingMessageAgent → Dispatcher →
scheduler turn → invoke (SURVEY.md §3.3) — with a vectorized dispatch tick
(§7): every event-loop iteration, all pending invocations per (class, method)
are packed into fixed-bucket batches and executed as ONE pjit'ed kernel over
the sharded actor table:

    gather rows → fresh-init (on-device activation) → vmapped handler
    → masked scatter (skipped for read-only methods)

run under ``shard_map`` so each mesh shard touches only its slot block
(gathers/scatters are shard-local; no cross-device traffic inside a tick —
cross-shard *messages* are the transport layer's job).

Turn-semantics guarantee: within a tick at most one message per activation;
same-activation conflicts defer to the next tick (the mailbox ordering of
``ActivationData.EnqueueMessage``, ActivationData.cs:566).

Static-shape discipline: batch buckets are powers of two with a floor, so
XLA compiles O(log max-batch) kernel variants per method, all reused across
ticks (no data-dependent shapes; SURVEY.md §7 hard parts #3).
"""

from __future__ import annotations

import asyncio
import logging
import queue as _queue
import threading
import time
import warnings
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..core.ids import GrainId
from ..observability.stats import INGEST_STATS as _INGEST
from ..parallel.mesh import SILO_AXIS, make_mesh, shard_map_compat
from .table import ShardedActorTable
from .vector_grain import ActorMethod, VectorGrain

_QUEUE_WAIT = _INGEST["queue_wait"]
_STAGING = _INGEST["staging"]
_TRANSFER = _INGEST["transfer"]
_TICK = _INGEST["tick"]
_MESSAGES = _INGEST["messages"]
# worker-side ledger stamp (cost attribution, observability.ledger): the
# payload rides the job's deferred-stats list and replays loop-side in
# _complete_job — the CostLedger is loop-confined like the registries
_LEDGER = object()

log = logging.getLogger("orleans.vector")

__all__ = ["VectorRuntime", "VectorActorRef"]

MIN_BUCKET = 8


def _bucket(n: int) -> int:
    return max(MIN_BUCKET, 1 << max(0, (n - 1).bit_length()))


def _emit(sink, st, key: str, value: float) -> None:
    """One stage observation: direct on the loop (inline tick), deferred
    into ``sink`` on the worker (StatsRegistry/Histogram are not
    thread-safe — concurrent += loses updates and a first-tick key
    insert can break the sampler's snapshot iteration — so worker-side
    measurements REPLAY loop-side in _complete_job; the timing itself
    is still stamped off-loop)."""
    if sink is not None:
        sink.append((key, value))
    else:
        st.observe(key, value)


async def join_poll(reduce_once, need: int, timeout: float | None,
                    poll: float) -> int:
    """The ONE join_when poll driver, shared by the engine surface
    (local reductions) and the client surface (one envelope per poll):
    await ``reduce_once()`` — a sum-reduction over the key set — until
    the first leaf reaches ``need`` or ``timeout`` elapses. Extracted so
    readiness semantics (leaf extraction, deadline handling) cannot
    drift between the two surfaces of the same primitive."""
    loop = asyncio.get_running_loop()
    deadline = None if timeout is None else loop.time() + timeout
    while True:
        val = await reduce_once()
        ready = 0
        if val is not None:
            leaves = jax.tree_util.tree_leaves(val)
            ready = int(leaves[0]) if leaves else 0
        if ready >= need:
            return ready
        if deadline is not None and loop.time() >= deadline:
            raise asyncio.TimeoutError(
                f"join_when: {ready}/{need} ready after {timeout}s")
        await asyncio.sleep(poll)


def _validate_args(cls: type, method: str, schema: dict, args: dict) -> None:
    missing = set(schema) - set(args)
    extra = set(args) - set(schema)
    if missing or extra:
        raise TypeError(
            f"{cls.__name__}.{method} args mismatch: "
            f"missing {sorted(missing)}, unexpected {sorted(extra)} "
            f"(schema: {sorted(schema)})")


class _DensePlan:
    """Cached batch layout for a recurring dense key set. The constant batch
    operands (slots/key-hashes/valid mask/zero fresh mask) are uploaded to
    device once and reused every tick — only the message payload crosses the
    host↔device boundary per round."""

    __slots__ = ("keys", "order", "inv", "sorted_shard", "lane_sorted", "B",
                 "slots_b", "valid_b", "khash_b", "_dev", "identity", "counts")

    def __init__(self, keys, order, inv, sorted_shard, lane_sorted, B,
                 slots_b, valid_b, khash_b, identity=False, counts=None):
        self.keys = keys
        self.order = order
        self.inv = inv
        self.sorted_shard = sorted_shard
        self.lane_sorted = lane_sorted
        self.B = B
        self.slots_b = slots_b
        self.valid_b = valid_b
        self.khash_b = khash_b
        self._dev = None
        # identity plans (keys == 0..M-1 under the block-wise dense mapping)
        # repack by contiguous slice copies instead of fancy indexing — the
        # zero-shuffle bulk path
        self.identity = identity
        self.counts = counts

    def pack(self, x: np.ndarray, dtype, shape) -> np.ndarray:
        """[M, ...] caller-order payload → [n_shards, B, ...] batch buffer."""
        n = self.valid_b.shape[0]
        buf = np.zeros((n, self.B, *shape), dtype=dtype)
        if self.identity:
            off = 0
            for s in range(n):
                c = self.counts[s]
                buf[s, :c] = x[off:off + c]
                off += c
        else:
            buf[self.sorted_shard, self.lane_sorted] = \
                np.asarray(x, dtype=dtype)[self.order]
        return buf

    def device_operands(self, put):
        if self._dev is None:
            self._dev = (
                put(jnp.asarray(self.slots_b)),
                put(jnp.asarray(self.khash_b)),
                put(jnp.asarray(self.valid_b)),
                put(jnp.zeros(self.valid_b.shape, jnp.bool_)),
            )
        return self._dev

    def unpack(self, results):
        """[n_shards, B, ...] device results → [M, ...] host rows in the
        caller's original key order (synchronizes)."""
        def one(a):
            a = np.asarray(a)
            if self.identity:
                return np.concatenate(
                    [a[s, :c] for s, c in enumerate(self.counts)])
            return a[self.sorted_shard, self.lane_sorted][self.inv]
        return jax.tree_util.tree_map(one, results)


class _StagingSet:
    """One preallocated ``[n_shards, B, ...]`` host staging buffer set for
    a (class, method) batch bucket: the batch operands (slots/key-hashes/
    fresh/valid) plus one array per schema field. Two sets per bucket
    alternate between "filling from ingress" and "donated to the tick
    kernel" (see ``VectorRuntime._staging_acquire``), so steady-state
    ingest never allocates — and never touches a buffer whose device
    upload could still be in flight."""

    __slots__ = ("slots", "khash", "fresh", "valid", "args", "used", "sink")

    def __init__(self, n: int, B: int, sink: int, schema: dict):
        self.slots = np.full((n, B), sink, dtype=np.int32)
        self.khash = np.zeros((n, B), dtype=np.int32)
        self.fresh = np.zeros((n, B), dtype=bool)
        self.valid = np.zeros((n, B), dtype=bool)
        self.args = {f: np.zeros((n, B, *shape), dtype=dtype)
                     for f, (dtype, shape) in schema.items()}
        self.used = [0] * n  # lanes filled per shard on the LAST use
        self.sink = sink     # the junk row every idle lane points at

    def reset(self, sink: int) -> None:
        """Re-arm for the next fill: only the previously-used lane prefix
        needs slots→sink + valid→False (stale khash/fresh/args lanes are
        inert once their slot is the junk sink row and valid is False;
        re-filled lanes are fully overwritten). When the sink itself
        moved — a table grow() turns the OLD sink row (== old capacity)
        into a real allocatable slot — every lane must re-point, not
        just the used prefix: a stale idle lane still aimed at the old
        sink would otherwise scatter into a live actor's row."""
        if sink != self.sink:
            self.slots[:] = sink
            self.valid[:] = False
            self.fresh[:] = False
            self.sink = sink
            self.used = [0] * len(self.used)
            return
        for s, c in enumerate(self.used):
            if c:
                self.slots[s, :c] = sink
                self.valid[s, :c] = False
            self.used[s] = 0


class _Pending:
    """One queued invocation in the hashed (per-key) path. ``t_enq`` is
    the monotonic enqueue stamp (0.0 with metrics off): the engine's
    queue-wait stage measures it against batch start, so tick-scheduling
    delay AND conflict-deferred extra ticks are attributed, on the owning
    silo only. ``future`` may be None (one-way batched-ingress calls —
    nothing consumes the per-lane result, so the batch skips the
    future/callback machinery for them entirely). ``trace`` is an
    optional ``(trace_id, parent_span_id)`` request trace context (set
    by the dispatcher's vector bridge and by the cross-process staging
    ring): a batch containing traced items records a correctly-parented
    device-tick child span even when the engine's own head-sample roll
    misses. ``origin`` labels the originating worker process for packed
    cross-process batches (ledger per-worker attribution); None for
    in-process calls."""

    __slots__ = ("key_hash", "shard", "slot", "fresh", "args", "future",
                 "t_enq", "trace", "origin")

    def __init__(self, key_hash, shard, slot, fresh, args, future,
                 t_enq=0.0, trace=None, origin=None):
        self.key_hash = key_hash
        self.shard = shard
        self.slot = slot
        self.fresh = fresh
        self.args = args
        self.future = future
        self.t_enq = t_enq
        self.trace = trace
        self.origin = origin


class _TickJob:
    """One claimed (class, method) batch bound for the off-loop tick
    worker. ``ready`` holds the conflict-free claim (turn semantics were
    decided loop-side); ``trace`` is the device-tick sampling roll (also
    loop-side — the SpanCollector is not thread-safe, so the worker only
    stamps timings and the completion callback records the span).
    ``per_shard``/``span`` are filled by the worker for the loop-side
    resolve; ``stats`` collects the worker's deferred stage observations
    — ``(key, value)`` with None = shed-trend note and _MESSAGES =
    counter increment — replayed loop-side (the registries are
    loop-confined)."""

    __slots__ = ("cls", "method", "ready", "trace", "per_shard", "span",
                 "stats")

    def __init__(self, cls, method, ready, trace=False):
        self.cls = cls
        self.method = method
        self.ready = ready
        self.trace = trace
        self.per_shard = None
        self.span = None
        self.stats: list = []


class VectorActorRef:
    """Typed handle to one device-tier activation (GrainReference analog)."""

    __slots__ = ("runtime", "grain_class", "key", "key_hash")

    def __init__(self, runtime: "VectorRuntime", grain_class: type, key: int,
                 key_hash: int):
        self.runtime = runtime
        self.grain_class = grain_class
        self.key = key
        self.key_hash = key_hash

    def __getattr__(self, name: str):
        self.runtime.method_of(self.grain_class, name)  # raise if unknown
        return partial(self.runtime.call, self.grain_class, self.key_hash, name)

    def __repr__(self) -> str:
        return f"VectorActorRef({self.grain_class.__name__}, {self.key!r})"


class VectorRuntime:
    """Per-silo device-tier runtime: tables + tick loop + kernel cache."""

    def __init__(self, mesh=None, capacity_per_shard: int = 1024,
                 options=None):
        if options is not None:  # config.DispatchOptions
            options.validate()
            capacity_per_shard = options.capacity_per_shard
        self.mesh = mesh if mesh is not None else make_mesh()
        self.capacity_per_shard = capacity_per_shard
        self.tables: dict[type, ShardedActorTable] = {}
        # pending per (class, method): list[_Pending]
        self.pending: dict[tuple[type, str], list[_Pending]] = {}
        # slots already claimed by the current tick per class → conflict defer
        self._tick_scheduled = False
        self._kernel_cache: dict[tuple, Any] = {}
        self._flush_waiters: list[asyncio.Future] = []
        self.ticks = 0
        self.messages_processed = 0
        self.exchange_lanes = 0  # device-valid lanes (see call_batch_device)
        # write-behind dirty tracking (off by default: marking 1M keys per
        # bulk tick is pure overhead unless a storage bridge consumes it)
        self.track_dirty = False
        self._dirty: dict[type, list[np.ndarray]] = {}
        # hot-spot load tracking (off by default, same rationale): when on,
        # every tick folds its batch into the table's on-device per-slot
        # hit counters — the telemetry feed of orleans_tpu.rebalance.
        # conflicts_deferred is the cumulative same-slot deferral count
        # (SiloControl's vector stats lens; always maintained, it's one
        # integer add on an already-deferring path)
        self.track_load = False
        self.conflicts_deferred = 0
        # double-buffered host staging (the batched-ingress hand-off):
        # per (class, method) → per buffer signature → two _StagingSets
        # alternating fill/in-flight, plus the last-batch fill count (the
        # sampler's staging-occupancy gauge)
        self._staging: dict[tuple, dict] = {}
        self.staging_fill = 0
        # load-shed queue-wait trend (observability.stats.QueueWaitTrend),
        # set by dispatch.hosting when the owning silo sheds on trend:
        # device batch starts feed it beside the INGEST queue_wait stage
        self.shed_trend = None
        # distributed-tracing collector (observability.tracing), set by
        # dispatch.hosting when the owning silo traces: each batch records
        # a "device_tick" span AND opens a jax.profiler.TraceAnnotation so
        # XLA kernels nest under the logical tick on a profiler capture
        self.tracer = None
        # ingest stage metrics (observability.stats.INGEST_STATS), set by
        # dispatch.hosting when the owning silo has metrics enabled: each
        # message batch splits into staging (pending -> host arrays),
        # transfer (host -> device operands), and tick (kernel dispatch +
        # device execution + host materialize) histograms — the device
        # half of the socket->tick ingest attribution
        self.stats = None
        # cost-attribution ledger (observability.ledger), set by
        # dispatch.hosting when the owning silo runs ledger_enabled: the
        # batch epilogue charges rows × tick wall to the (class, method)
        # row and the per-key sketch; track_cost mirrors track_load for
        # the on-device per-slot cost twin (table.record_cost)
        self.ledger = None
        self.track_cost = False
        # host-loop occupancy profiler (observability.profiling), set by
        # the owning silo when profiling_enabled: each tick callback is
        # segmented into tick_schedule / tick_staging / tick_transfer /
        # tick_sync occupancy slices — tick_sync (host materialize, where
        # async device dispatch is actually paid) is the loop time the
        # off-loop-sync lever would reclaim
        self.loop_prof = None
        # stateless-worker (mesh-replicated) hosts per class — see
        # dispatch.replicated (StatelessWorkerPlacement.cs:6 on device)
        self._replicated_hosts: dict[type, Any] = {}
        # off-loop tick pipeline (SiloConfig.offloop_tick /
        # DispatchOptions.offloop_tick): when enabled, claimed batches run
        # on a dedicated per-engine worker thread — staging fill, operand
        # upload, kernel dispatch, and the host materialize sync all leave
        # the event loop; the loop-side _tick shrinks to claim/conflict-
        # defer plus a queue hand-off, and futures resolve back on the
        # loop via call_soon_threadsafe. The _fence is the tick-
        # serialization lock: the worker holds it for the whole batch
        # (donated state + donated staging operands are in flight), and
        # loop-side table mutation/materialization — grow(), shard moves,
        # bulk call_batch*, checkpoint capture, write-behind gathers —
        # takes it around the touch so neither side ever sees a donated
        # buffer mid-dispatch. Worker FIFO order serializes state
        # donation per table (tick N+1 runs strictly after tick N's sync
        # proved N's uploads complete, so staging lanes never rotate back
        # to "filling" under an in-flight transfer).
        self.offloop_tick = bool(getattr(options, "offloop_tick", False)) \
            if options is not None else False
        self._fence = threading.RLock()
        self._worker: threading.Thread | None = None
        self._worker_q: "_queue.SimpleQueue | None" = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._quiesced: asyncio.Event | None = None
        self._complete_ctx = None  # tick_schedule-labeled completion ctx
        self._inflight = 0        # jobs handed to the worker, unresolved
        self._inflight_msgs = 0   # messages inside those jobs
        # class -> {key_hash: count} for in-flight jobs: these keys are
        # FENCED exactly like pending ones (pending_key_hashes) — a
        # migration moving one mid-flight would let the worker's scatter
        # land in the abandoned source row
        self._inflight_keys: dict[type, dict[int, int]] = {}
        # lax.scan unroll for scanned (call_batch_rounds) kernels: each
        # scan step carries a fixed per-iteration cost (loop bookkeeping,
        # staged-payload dynamic slicing) that dominates small-population
        # rounds; unrolling amortizes it across U rounds per step at the
        # cost of a longer compile. 1 = plain scan
        self.scan_unroll = 1

    def validate_pipeline_depth(self, depth: int,
                                allow_unproven: bool = False) -> int:
        """Refuse to keep more than one super-round in flight on a
        multi-shard mesh.

        Overlapping collective programs (the ``all_to_all`` route fabric)
        DEADLOCK the single-host CPU backend: concurrently-executing
        programs contend for the shared cross-device rendezvous pool, and
        two half-started all_to_alls each hold rendezvous slots the other
        needs. On real multi-chip hardware the combination (fused pipeline
        × collectives) has never been executed by this runtime, so it is
        refused there too until proven; pass ``allow_unproven=True`` to
        try it on a non-CPU backend at your own risk. Single-shard meshes
        run no collectives and pipeline freely."""
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        n_dev = int(self.mesh.devices.size)
        if depth > 1 and n_dev > 1:
            platform = self.mesh.devices.flat[0].platform
            if platform == "cpu" or not allow_unproven:
                raise ValueError(
                    f"pipeline_depth={depth} is not supported on a "
                    f"{n_dev}-shard mesh ({platform}): overlapping "
                    "collective programs deadlock the CPU backend's "
                    "shared rendezvous pool, and the combination is "
                    "unproven on multi-chip hardware. Run cross-shard "
                    "supers at depth 1 (sequential), or pass "
                    "allow_unproven=True on a non-CPU backend.")
        return depth

    def replicated_host(self, cls: type, n_keys: int | None = None):
        """Host ``cls`` as a mesh-replicated stateless worker (no
        directory entry; any shard serves any key; reads fan in via the
        class's MERGE collectives). ``n_keys`` is required on first call."""
        host = self._replicated_hosts.get(cls)
        if host is None:
            if n_keys is None:
                raise ValueError(
                    f"first replicated_host({cls.__name__}) needs n_keys")
            from .replicated import ReplicatedWorkerHost
            host = ReplicatedWorkerHost(cls, self.mesh, n_keys)
            self._replicated_hosts[cls] = host
        elif n_keys is not None and n_keys != host.n_keys:
            raise ValueError(
                f"{cls.__name__} already hosted with n_keys="
                f"{host.n_keys}; cannot re-host with n_keys={n_keys}")
        return host

    # ------------------------------------------------------------------
    def register(self, *grain_classes: type[VectorGrain],
                 capacity_per_shard: int | None = None) -> None:
        for cls in grain_classes:
            if cls not in self.tables:
                self.tables[cls] = ShardedActorTable(
                    cls, self.mesh,
                    capacity_per_shard or self.capacity_per_shard)
                # tick-serialization fence: table-level state mutators/
                # materializers (grow, move_rows, snapshot/restore,
                # read_row) serialize against worker-side batch execution
                # through the engine's lock (uncontended no-op inline)
                self.tables[cls].fence = self._fence
                if self.track_load:
                    self.tables[cls].enable_hit_tracking()
                if self.track_cost:
                    self.tables[cls].enable_cost_tracking()

    def table(self, cls: type) -> ShardedActorTable:
        if cls not in self.tables:
            self.register(cls)
        return self.tables[cls]

    def method_of(self, cls: type, name: str) -> ActorMethod:
        m = self.table(cls).methods.get(name)
        if m is None:
            raise AttributeError(
                f"{cls.__name__} has no @actor_method {name!r}")
        return m

    @staticmethod
    def key_hash_for(key, uniform_hash: int) -> int:
        """The one key→hash rule for both entry points (in-process
        VectorActorRefs and the dispatcher's client bridge): small
        non-negative int keys map directly (enabling the dense regime);
        everything else uses the GrainId uniform hash."""
        if isinstance(key, int) and 0 <= key < 2**62:
            return key
        return uniform_hash

    def actor(self, grain_class: type, key: int | str) -> VectorActorRef:
        """Reference to one device-tier activation."""
        from ..core.ids import GrainType
        gid = GrainId.for_grain(GrainType.of(grain_class.__name__), key)
        kh = self.key_hash_for(key, gid.uniform_hash)
        self.table(grain_class).note_route(kh, gid.uniform_hash)
        return VectorActorRef(self, grain_class, key, kh)

    # ------------------------------------------------------------------
    # Per-key path (general; conflict-safe)
    # ------------------------------------------------------------------
    def call(self, grain_class: type, key_hash: int, method: str,
             **args) -> asyncio.Future:
        """Queue one invocation; resolves after the tick that runs it."""
        m = self.method_of(grain_class, method)
        if m.args_schema is not None:
            _validate_args(grain_class, method, m.args_schema, args)
        tbl = self.table(grain_class)
        if 0 <= key_hash < tbl.dense_n:
            shard = key_hash // tbl.dense_per_shard
            slot = key_hash % tbl.dense_per_shard
            # first touch of a dense-provisioned key still needs its
            # on-device initial_state (the OnActivate analog)
            fresh = not bool(tbl.dense_active[key_hash])
            tbl.dense_active[key_hash] = True
        else:
            shard, slot, fresh = tbl.lookup_or_allocate(key_hash)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self.pending.setdefault((grain_class, method), []).append(
            _Pending(key_hash, shard, slot, fresh, args, fut,
                     time.monotonic()
                     if (self.stats is not None
                         or self.shed_trend is not None) else 0.0))
        self._schedule_tick(loop)
        return fut

    def call_group(self, grain_class: type, method: str,
                   items: list, traces: list | None = None,
                   origin: str | None = None) -> list:
        """Grouped enqueue — the engine half of the batched ingress
        hand-off. ``items`` is a list of ``(key_hash, kwargs,
        want_future)`` triples for ONE (class, method); every invocation
        joins the pending batch with a single method/table resolution,
        one enqueue stamp, and one tick schedule, instead of N
        :meth:`call` hops. Returns one entry per item in item order
        (within-batch arrival order is preserved into the tick's lane
        layout): a future where ``want_future`` was set, else None —
        one-way calls skip the future/set_result/callback machinery
        entirely, which is a large slice of the per-message hand-off
        cost at batch sizes. A per-item schema violation resolves THAT
        item's future with the error (or drops the one-way item, the
        per-message one-way contract); the rest of the group proceeds.

        ``traces`` is an optional parallel list of per-item
        ``(trace_id, parent_span_id)`` contexts (None entries for
        untraced items): the tick records a correctly-parented
        device-tick child span for each distinct context. ``origin``
        labels every item with the originating worker process (the
        cross-process ledger attribution key)."""
        m = self.method_of(grain_class, method)
        schema = m.args_schema
        skeys = schema.keys() if schema is not None else None
        tbl = self.table(grain_class)
        loop = asyncio.get_running_loop()
        t_enq = time.monotonic() if (self.stats is not None or
                                     self.shed_trend is not None or
                                     traces is not None) else 0.0
        pend: list | None = None  # created on first ENQUEUED item so an
        # all-failed group never leaves an empty pending entry behind (a
        # tick over it would crash first-batch schema inference)
        dense_n, per = tbl.dense_n, tbl.dense_per_shard
        futs: list = []
        idx = -1
        for key_hash, args, want_future in items:
            idx += 1
            fut = loop.create_future() if want_future else None
            futs.append(fut)
            try:
                if skeys is not None and args.keys() != skeys:
                    _validate_args(grain_class, method, schema, args)
                if 0 <= key_hash < dense_n:
                    shard = key_hash // per
                    slot = key_hash % per
                    fresh = not bool(tbl.dense_active[key_hash])
                    tbl.dense_active[key_hash] = True
                else:
                    shard, slot, fresh = tbl.lookup_or_allocate(key_hash)
            except Exception as e:  # noqa: BLE001 — schema violation or
                # slot-allocation failure: scoped to THIS item (a raise
                # escaping mid-loop would error-bounce the whole group
                # while already-enqueued items still tick)
                if fut is not None:
                    fut.set_exception(e)
                continue
            if pend is None:
                pend = self.pending.setdefault((grain_class, method), [])
            pend.append(_Pending(key_hash, shard, slot, fresh, args, fut,
                                 t_enq,
                                 traces[idx] if traces is not None else None,
                                 origin))
        if pend is not None:
            self._schedule_tick(loop)
        return futs

    def call_packed(self, grain_class: type, method: str, key_hashes: list,
                    columns: dict, wants: list,
                    traces: list | None = None,
                    origin: str | None = None) -> list:
        """Columnar enqueue — the owner-process half of the cross-process
        staging ring (runtime.multiproc): a worker packs one ingress
        batch's calls column-major (one ``columns[name]`` list per
        argument) into the shared segment, and this unpacks them into
        the SAME pending batch ``call_group`` would have built — one
        method/table resolution, one enqueue stamp, one tick schedule
        for the whole record, and bit-for-bit the ``call_group`` result
        semantics (that is what the shm-parity test asserts).
        ``traces``/``origin`` carry the ring record's per-sub trace
        contexts and originating-worker label through to the tick (see
        :meth:`call_group`).

        Deliberately NOT a direct scatter into the ``[n_shards, B]``
        staging buffers: lane allocation is owner state under the tick
        fence (slot lookup, conflict deferral, double-buffer rotation),
        so the fence-owning process does the staging fill exactly as it
        does for in-process calls."""
        names = tuple(columns)
        cols = [columns[n] for n in names]
        return self.call_group(grain_class, method, [
            (kh, {n: col[i] for n, col in zip(names, cols)}, want)
            for i, (kh, want) in enumerate(zip(key_hashes, wants))],
            traces=traces, origin=origin)

    # -- write-behind dirty tracking (consumed by storage.checkpoint) ----
    def enable_dirty_tracking(self) -> None:
        self.track_dirty = True

    # -- hot-spot load telemetry (consumed by orleans_tpu.rebalance) -----
    def enable_load_tracking(self) -> None:
        self.track_load = True
        for tbl in self.tables.values():
            tbl.enable_hit_tracking()

    # -- per-slot cost telemetry (consumed by observability.ledger) ------
    def enable_cost_tracking(self) -> None:
        self.track_cost = True
        for tbl in self.tables.values():
            tbl.enable_cost_tracking()

    def queue_depth(self) -> int:
        """Invocations queued for future ticks (incl. conflict-deferred
        and batches in flight on the off-loop worker) — the device tier's
        inbound-queue-depth load signal."""
        return sum(len(v) for v in self.pending.values()) + \
            self._inflight_msgs

    def pending_key_hashes(self, cls: type) -> set[int]:
        """Keys with queued invocations for ``cls``, plus keys inside
        batches currently executing on the off-loop worker. Queued
        ``_Pending`` entries cache their (shard, slot), so these keys are
        FENCED: a migration moving one mid-flight would let the next (or
        in-flight) tick scatter into the abandoned source row."""
        keys = {p.key_hash for (c, _m), items in self.pending.items()
                if c is cls for p in items}
        ctr = self._inflight_keys.get(cls)
        if ctr:
            keys.update(ctr)
        return keys

    def shard_loads(self) -> dict[type, np.ndarray]:
        """Per-class per-shard invocation totals since the last reset."""
        return {cls: tbl.shard_hits() for cls, tbl in self.tables.items()}

    def _mark_dirty(self, cls: type, keys) -> None:
        if self.track_dirty:
            self._dirty.setdefault(cls, []).append(
                np.atleast_1d(np.asarray(keys)))

    def drain_dirty(self, cls: type) -> np.ndarray:
        """Keys written since the last drain (deduplicated). The pop is
        under the tick fence: ``_mark_dirty`` runs worker-side inside an
        off-loop batch (which holds the fence for its whole duration),
        so an unfenced pop could orphan a list the worker is about to
        append to — keys written by that batch would silently never
        flush. Uncontended no-op on the inline path."""
        with self._fence:
            batches = self._dirty.pop(cls, None)
        if not batches:
            return np.zeros(0, dtype=np.int64)
        return np.unique(np.concatenate(batches))

    def _staging_acquire(self, cls: type, method: str, tbl,
                         B: int, schema: dict) -> _StagingSet:
        """Check out the "filling" half of the double-buffered staging
        pair for this (class, method, B, schema) bucket. The OTHER half
        is the one the in-flight tick's device upload consumed — by the
        time a buffer rotates back here its tick has synced (the batch
        materializes results on host before resolving futures), so
        refilling can never race a kernel still reading it."""
        pool = self._staging.get((cls, method))
        if pool is None:
            pool = self._staging[(cls, method)] = {}
        sig = (tbl.n_shards, B, tuple(sorted(
            (f, np.dtype(d).str, tuple(int(x) for x in shape))
            for f, (d, shape) in schema.items())))
        entry = pool.get(sig)
        if entry is None:
            entry = pool[sig] = [[], 0]
        sets, idx = entry
        if len(sets) < 2:
            st = _StagingSet(tbl.n_shards, B, tbl.sink_slot, schema)
            sets.append(st)
            entry[1] = len(sets) % 2
            return st
        st = sets[idx]
        entry[1] = idx ^ 1
        st.reset(tbl.sink_slot)
        return st

    def staging_lanes(self) -> int:
        """Total preallocated staging lanes across every double-buffer
        set (the staging-buffer footprint gauge). Read loop-side while
        the off-loop worker may be growing the pools — retried on a
        concurrent-mutation error rather than fenced (the sampler must
        never block the loop behind an in-flight batch)."""
        for _ in range(4):
            try:
                total = 0
                for pool in list(self._staging.values()):
                    for (n, B, _sig), (sets, _idx) in list(pool.items()):
                        total += n * B * len(sets)
                return total
            except RuntimeError:  # dict mutated during iteration
                continue
        return 0

    def _schedule_tick(self, loop) -> None:
        if not self._tick_scheduled:
            self._tick_scheduled = True
            loop.call_soon(self._tick)

    # -- off-loop tick worker ------------------------------------------
    def tick_fence(self):
        """The tick-serialization fence (a reentrant lock usable as a
        context manager): loop-side code that mutates or materializes
        table state outside the tick path — rebalance shard moves,
        checkpoint capture, write-behind gathers — takes it around the
        touch so it can never interleave with a worker-side batch whose
        donated state/staging upload is still in flight. Uncontended
        (and effectively free) on the inline path."""
        return self._fence

    def _ensure_worker(self) -> None:
        if self._worker is not None:
            return
        import contextvars

        from ..observability.profiling import LOOP_CATEGORY
        self._loop = asyncio.get_running_loop()
        self._worker_q = _queue.SimpleQueue()
        self._quiesced = asyncio.Event()
        self._quiesced.set()
        # completion callbacks run loop-side in THIS prebuilt context so
        # the profiler books them to tick_schedule — the same category
        # the inline path's resolution work carries. Scheduling from the
        # worker thread would otherwise capture an unset context and the
        # per-batch resolve/replay would book to "other", biasing the
        # inline-vs-offloop tick-share A/B exactly where it is read.
        self._complete_ctx = contextvars.Context()
        self._complete_ctx.run(LOOP_CATEGORY.set, "tick_schedule")
        t = threading.Thread(target=self._worker_main,
                             name="orleans-tick-worker", daemon=True)
        self._worker = t
        t.start()

    def shutdown_worker(self, timeout: float = 10.0) -> None:
        """Stop the off-loop tick worker (silo stop): jobs already queued
        finish FIFO, then the thread exits. Completion callbacks posted
        to the loop still run when control next returns to it. Idempotent
        and a no-op on the inline path; a later tick after shutdown would
        lazily start a fresh worker (restart-in-process)."""
        w, self._worker = self._worker, None
        if w is None:
            return
        self._worker_q.put(None)
        w.join(timeout)

    def _worker_main(self) -> None:
        q = self._worker_q
        while True:
            job = q.get()
            if job is None:
                return
            host = err = None
            try:
                # the fence is held for the WHOLE batch: donated tbl.state
                # and donated staging operands are in flight until the
                # sync at the end of _execute_batch proves the uploads
                # completed
                with self._fence:
                    job.per_shard, host, job.span = self._execute_batch(
                        job.cls, job.method, job.ready, None,
                        trace_roll=job.trace, sink=job.stats)
            except BaseException as e:  # noqa: BLE001 — futures fail loop-side
                err = e
            try:
                self._loop.call_soon_threadsafe(
                    self._complete_job, job, host, err,
                    context=self._complete_ctx)
            except RuntimeError:
                # loop closed (ungraceful stop): the runtime client is
                # breaking outstanding futures; nothing left to resolve
                return

    def _submit_job(self, job: _TickJob) -> None:
        self._ensure_worker()
        self._inflight += 1
        self._inflight_msgs += len(job.ready)
        self._quiesced.clear()
        ctr = self._inflight_keys.setdefault(job.cls, {})
        for p in job.ready:
            ctr[p.key_hash] = ctr.get(p.key_hash, 0) + 1
        self._worker_q.put(job)

    def _record_tick_span(self, span, ready: list, error: bool = False
                          ) -> None:
        """Loop-side record of a device-tick span from worker- (or
        inline-) stamped timings; ``span`` = (name, wall_start,
        duration[, batch_wall, batch_mono]) or None. The error form is
        what tail retention keys on, so failing sampled ticks stay
        visible in retained traces.

        Items carrying a request trace context additionally get (a) a
        device-tick child span parented into THEIR trace, spanning
        batch start (staging fill) through host materialize — the
        owner-side leg of the cross-process waterfall — and (b) a
        queue-wait server span covering enqueue → batch start, so the
        ring-dwell / queue-wait / tick segments read contiguously. One
        pair per distinct context (the tick is one event)."""
        tracer = self.tracer
        if span is None or tracer is None:
            return
        name, start_wall, dur = span[0], span[1], span[2]
        n = len(ready)
        if error:
            tracer.record(tracer.device_trace_id, None, name,
                          "device_tick", start_wall, dur, batch=n,
                          error=True)
        else:
            tracer.record(tracer.device_trace_id, None, name,
                          "device_tick", start_wall, dur, batch=n)
        if len(span) < 5:
            return
        batch_wall, batch_mono = span[3], span[4]
        end_wall = start_wall + dur
        seen: set = set()
        for p in ready:
            tr = p.trace
            if tr is None or tr in seen:
                continue
            seen.add(tr)
            tid, psid = tr
            if error:
                tracer.record(tid, psid, name, "device_tick", batch_wall,
                              max(0.0, end_wall - batch_wall), batch=n,
                              error=True)
            else:
                tracer.record(tid, psid, name, "device_tick", batch_wall,
                              max(0.0, end_wall - batch_wall), batch=n)
            if p.t_enq and batch_mono > p.t_enq:
                q = batch_mono - p.t_enq
                tracer.record(tid, psid, "engine.queue_wait", "server",
                              batch_wall - q, q, queue_s=q, exec_s=0.0)

    def _complete_job(self, job: _TickJob, host, err) -> None:
        """Loop-side completion: resolve futures (or fail them), record
        the sampled device-tick span (the collector is loop-confined;
        the worker only stamped timings), and — in a finally, so no
        resolve/record error can ever wedge it — release the in-flight
        key fence and re-arm the quiescence event. A loop-side failure
        here fails the batch's futures like the inline path's tick
        except does; it never leaves callers hanging."""
        try:
            # replay the worker's deferred observations into the loop-
            # confined registries (timings were stamped off-loop); on an
            # errored batch the list holds whatever stages completed
            if job.stats:
                st = self.stats
                trend = self.shed_trend
                for key, val in job.stats:
                    if key is None:
                        if trend is not None:
                            trend.note(val)
                    elif key is _LEDGER:
                        # NOT metrics-gated: the ledger runs with the
                        # stats registry off (sanctioned replay — the
                        # worker stamped, the loop charges)
                        if self.ledger is not None:
                            self.ledger.charge_tick(val)
                    elif st is None:
                        continue
                    elif key is _MESSAGES:
                        st.increment(key, val)
                    else:
                        st.observe(key, val)
            if err is not None:
                log.error("vector tick failed for %s.%s",
                          job.cls.__name__, job.method, exc_info=err)
                self._record_tick_span(getattr(err, "_tick_span", None),
                                       job.ready, error=True)
                for p in job.ready:
                    if p.future is not None and not p.future.done():
                        p.future.set_exception(err)
            else:
                self._record_tick_span(job.span, job.ready)
                self._resolve_batch(job.ready, job.per_shard, host)
        except BaseException as e2:  # noqa: BLE001 — fail futures, not loop
            log.exception("vector tick completion failed for %s.%s",
                          job.cls.__name__, job.method)
            for p in job.ready:
                if p.future is not None and not p.future.done():
                    p.future.set_exception(e2)
        finally:
            self._inflight -= 1
            self._inflight_msgs -= len(job.ready)
            ctr = self._inflight_keys.get(job.cls)
            if ctr is not None:
                for p in job.ready:
                    left = ctr.get(p.key_hash, 0) - 1
                    if left <= 0:
                        ctr.pop(p.key_hash, None)
                    else:
                        ctr[p.key_hash] = left
            if self._inflight == 0:
                self._quiesced.set()

    async def flush(self) -> None:
        """Run ticks until all pending work (incl. conflict-deferred and
        worker-side in-flight batches) drains. Identical to the
        historical tick-and-yield spin on the inline path; with the
        off-loop worker it awaits the worker's quiescence event between
        rounds instead of busy-spinning the loop."""
        while self.pending or self._inflight:
            if self.pending:
                self._tick()
            if self._inflight:
                await self._quiesced.wait()
            else:
                await asyncio.sleep(0)

    # ------------------------------------------------------------------
    def _tick(self) -> None:
        self._tick_scheduled = False
        if not self.pending:
            return
        lp = self.loop_prof
        if lp is not None:
            # this call_soon callback IS the device tick: everything not
            # re-segmented below (claiming, conflict defer, rescheduling,
            # worker hand-off) is tick scheduling work on the loop
            lp.set_category("tick_schedule")
        work, self.pending = self.pending, {}
        offloop = self.offloop_tick
        tracer = self.tracer
        for (cls, method), items in work.items():
            ready = self._claim(cls, method, items)
            if not ready:
                continue
            # device-tick sampling rolls HERE (loop-side) on both paths:
            # the worker must not touch the collector. A batch carrying
            # request trace contexts (threaded over the cross-process
            # staging ring or the vector bridge) records regardless of
            # the roll: header presence IS the upstream sampled decision
            roll = tracer is not None and (
                tracer.sample()
                or any(p.trace is not None for p in ready))
            if offloop:
                self._submit_job(_TickJob(cls, method, ready, roll))
                continue
            try:
                self._run_batch(cls, method, ready, trace_roll=roll)
            except Exception as e:  # noqa: BLE001 — fail the futures, not the loop
                log.exception("vector tick failed for %s.%s",
                              cls.__name__, method)
                self._record_tick_span(getattr(e, "_tick_span", None),
                                       ready, error=True)
                for p in ready:
                    if p.future is not None and not p.future.done():
                        p.future.set_exception(e)
        self.ticks += 1
        if self.pending:  # conflict-deferred work → next tick
            self._schedule_tick(asyncio.get_running_loop())

    def _claim(self, cls: type, method: str,
               items: list[_Pending]) -> list[_Pending]:
        """Turn-semantics claim, always loop-side (it mutates
        ``self.pending``): one message per slot per tick; same-slot
        conflicts defer to the next tick."""
        claimed: set[tuple[int, int]] = set()
        ready: list[_Pending] = []
        for p in items:
            loc = (p.shard, p.slot)
            if loc in claimed:
                self.pending.setdefault((cls, method), []).append(p)
                self.conflicts_deferred += 1
                continue
            claimed.add(loc)
            ready.append(p)
        return ready

    def _run_batch(self, cls: type, method: str, ready: list[_Pending],
                   trace_roll: bool = False) -> None:
        """Inline (on-loop) batch execution — the ``offloop_tick=False``
        path, semantically today's tick. Runs under the tick fence like
        the worker path: the loop being the only ticker does NOT make
        the donated state safe — checkpoint capture() is documented
        callable from any thread, and a worker batch may still be in
        flight when offloop_tick is flipped off (restart-in-process).
        Uncontended reentrant acquire is ~100ns against a multi-ms tick."""
        with self._fence:
            per_shard, host, span = self._execute_batch(
                cls, method, ready, self.loop_prof, trace_roll=trace_roll)
        self._record_tick_span(span, ready)
        self._resolve_batch(ready, per_shard, host)

    def _resolve_batch(self, ready: list[_Pending], per_shard,
                       host) -> None:
        for s, ps in enumerate(per_shard):
            for i, p in enumerate(ps):
                if p.future is not None and not p.future.done():
                    p.future.set_result(jax.tree_util.tree_map(
                        lambda a: a[s, i], host))
        self.messages_processed += len(ready)

    def _execute_batch(self, cls: type, method: str, ready: list[_Pending],
                       lp, trace_roll: bool = False, sink: list | None = None):
        """Staging fill → operand upload → kernel dispatch → host
        materialize sync for one claimed, conflict-free batch. Runs on
        the loop (inline path; ``lp`` is the loop profiler, ``sink``
        None — observations go straight to the registry) or on the
        off-loop tick worker (``lp`` None — worker wall time is not loop
        time and the profiler's attribution state is loop-confined;
        ``sink`` = the job's deferred-stats list — timings are STAMPED
        here off-loop but recorded loop-side in _complete_job, because
        StatsRegistry/Histogram/QueueWaitTrend are not thread-safe).
        Returns ``(per_shard, host_results, span_timing)`` where
        ``span_timing`` is ``(name, wall_start, duration)`` for a sampled
        tick (recorded by the caller on the loop) or None."""
        st = self.stats
        led = self.ledger
        if lp is not None:
            # loop occupancy: staging-fill from here; the label tuple
            # names this batch in the flight recorder's top-K and is only
            # string-joined on admission — every tick pays no format
            lp.set_category("tick_staging", ("tick", cls.__name__, method))
        t_stage = now_mono = batch_wall = 0.0
        if st is not None:
            t_stage = time.perf_counter()
        if st is not None or self.shed_trend is not None or trace_roll:
            now_mono = time.monotonic()  # queue-wait ends at batch start
            # (the shed trend needs the stamp even with metrics off —
            # t_enq is gated the same way in call/call_group; traced
            # batches need it for the queue-wait child span)
        if trace_roll:
            # wall twin of the batch-start stamp: the traced device-tick
            # child span opens HERE (staging fill onward), so the
            # waterfall's queue-wait → staging/transfer/tick segments
            # are contiguous (the sampled device_trace_id span keeps its
            # kernel-dispatch-onward semantics)
            batch_wall = time.time()
        tbl = self.tables[cls]
        m = tbl.methods[method]
        # schema inference is committed only after a successful batch so a
        # bad first call cannot poison the class-level schema
        schema = m.args_schema
        inferred = schema is None
        if inferred:
            schema = {k: (np.asarray(v).dtype, np.asarray(v).shape)
                      for k, v in ready[0].args.items()}
        n, cap = tbl.n_shards, tbl.capacity
        per_shard: list[list[_Pending]] = [[] for _ in range(n)]
        for p in ready:
            per_shard[p.shard].append(p)
        B = _bucket(max(len(ps) for ps in per_shard))
        # double-buffered staging: one preallocated buffer set fills here
        # while its twin may still back the previous tick's device upload
        # — steady-state ingest allocates nothing host-side
        stg = self._staging_acquire(cls, method, tbl, B, schema)
        slots, khash = stg.slots, stg.khash
        fresh, valid = stg.fresh, stg.valid
        args_stacked = stg.args
        for s, ps in enumerate(per_shard):
            stg.used[s] = len(ps)
            for i, p in enumerate(ps):
                slots[s, i] = p.slot
                # key hashes ride to the device as 31-bit ints (x64 is
                # disabled; initial_state only needs a per-actor seed)
                khash[s, i] = p.key_hash & 0x7FFFFFFF
                fresh[s, i] = p.fresh
                valid[s, i] = True
                for fname in schema:
                    args_stacked[fname][s, i] = p.args[fname]
        self.staging_fill = len(ready)
        if lp is not None:
            # staging done: operand upload + kernel dispatch next
            lp.set_category("tick_transfer")
        if inferred:
            m.args_schema = schema  # needed by the kernel builder
        t_xfer = t_tick = 0.0
        if st is not None:
            t_xfer = time.perf_counter()
            _emit(sink, st, _STAGING, t_xfer - t_stage)
            # per-item queue wait: enqueue (rt.call) -> this batch start —
            # tick scheduling plus any conflict-deferred full ticks; items
            # enqueued by non-call paths carry no stamp and are skipped
            for p in ready:
                if p.t_enq:
                    _emit(sink, st, _QUEUE_WAIT,
                          max(0.0, now_mono - p.t_enq))
        if self.shed_trend is not None:
            # feed the load-shed trend with this batch's mean queue wait
            # (deferred to the loop-side completion on the worker path:
            # QueueWaitTrend is not thread-safe, and the dispatcher feeds
            # it from the loop)
            stamped = [now_mono - p.t_enq for p in ready if p.t_enq]
            if stamped:
                mean = max(0.0, sum(stamped) / len(stamped))
                if sink is not None:
                    sink.append((None, mean))
                else:
                    self.shed_trend.note(mean)
        span_name = span_start = t_span0 = None
        try:
            # operand buffers are donated: these device arrays are fresh
            # per tick (never the cached _DensePlan operands), so XLA may
            # reuse them as the kernel's output/scratch — the device_put
            # below becomes a donation hand-off, not a second copy
            kernel = self._kernel(cls, method, B, donate_operands=True)
            kernel_args = (
                tbl.state, jnp.asarray(slots), jnp.asarray(khash),
                jnp.asarray(fresh), jnp.asarray(valid),
                {k: jnp.asarray(v) for k, v in args_stacked.items()})
            if st is not None:
                t_tick = time.perf_counter()
                _emit(sink, st, _TRANSFER, t_tick - t_xfer)
            elif led is not None:
                t_tick = time.perf_counter()  # ledger-only tick wall start
            if trace_roll:
                span_name = f"tick {cls.__name__}.{method}"
                span_start = time.time()
                t_span0 = time.perf_counter()
                # the TraceAnnotation bridges host tracing to the XLA
                # timeline: on a jax.profiler capture, this tick's
                # kernels nest under a span named like the logical tick
                # span. Gated on the SAMPLED tick (rolled loop-side) so
                # unsampled/untraced silos pay nothing per batch flush.
                with jax.profiler.TraceAnnotation(span_name):
                    new_state, results = kernel(*kernel_args)
            else:
                new_state, results = kernel(*kernel_args)
        except BaseException as e:
            if inferred:
                m.args_schema = None  # do not poison the class schema
            if span_start is not None:
                # a sampled tick whose kernel raised still records an
                # errored device span (tail retention keys on the error
                # attr) — the collector is loop-confined, so the timing
                # rides the exception to the loop-side completion/except
                # (best-effort: an exception type rejecting attributes
                # just loses the span, never the error)
                try:
                    e._tick_span = (span_name, span_start,
                                    time.perf_counter() - t_span0,
                                    batch_wall, now_mono)
                except AttributeError:
                    pass
            raise
        if not m.read_only:
            tbl.state = new_state
            # dirty marks happen at state-apply time, not enqueue time: a
            # write-behind flush between enqueue and tick would otherwise
            # drain the key and persist the pre-write row forever
            self._mark_dirty(cls, np.fromiter(
                (p.key_hash for p in ready), dtype=np.int64,
                count=len(ready)))
        if self.track_load:
            tbl.record_hits(slots, valid)
        if lp is not None:
            # THE distinct device-sync occupancy (inline path only): jax
            # dispatch is async, so the host materialize below is where
            # device execution is actually paid on the loop — the slice
            # the off-loop worker removes from the loop entirely
            lp.set_category("tick_sync")
        host = jax.tree_util.tree_map(np.asarray, results)
        if not jax.tree_util.tree_leaves(host):
            # result-less method: no np.asarray above synced anything, so
            # block on the state output before this tick's staging
            # buffers can rotate back to "filling" — on async-transfer
            # backends (TPU) the operands' host→device upload must have
            # provably completed before the numpy buffers are reused
            # (free on CPU, where the transfer copies synchronously).
            # This sync is ALSO the off-loop staging pin: the worker runs
            # batches FIFO, so by the time a staging set rotates back its
            # tick has provably synced here.
            jax.block_until_ready(new_state)
        if st is not None:
            # tick closes AFTER the host transfer for the same reason the
            # span timing does: jax dispatch is async, and the np.asarray
            # sync is where device execution is actually paid
            _emit(sink, st, _TICK, time.perf_counter() - t_tick)
            if sink is not None:
                sink.append((_MESSAGES, len(ready)))
            else:
                st.increment(_MESSAGES, len(ready))
        if led is not None:
            # cost-attribution epilogue: every resident row is charged
            # this tick's wall (row-seconds = rows × wall); the per-slot
            # device twin folds the same batch via record_cost (the
            # _accumulate_hits scatter with the µs charge as scale).
            # Worker path stamps the payload for loop-side replay —
            # same discipline as the stage observations above.
            tick_s = max(0.0, time.perf_counter() - t_tick)
            payload = (cls.__name__, method, len(ready), tick_s,
                       tuple(f"{cls.__name__}#{p.key_hash}"
                             for p in ready))
            if any(p.origin is not None for p in ready):
                # cross-process batch: per-item originating-worker labels
                # ride as a parallel 6th element (the ledger's per-process
                # device-time attribution key); in-process payloads stay
                # 5-tuples so merged snapshots are stable across versions
                payload = payload + (
                    tuple(p.origin for p in ready),)
            if sink is not None:
                sink.append((_LEDGER, payload))
            else:
                led.charge_tick(payload)
            if self.track_cost:
                tbl.record_cost(slots, valid, int(tick_s * 1e6))
        span = None
        if trace_roll and span_name is not None:
            # duration closes AFTER the host transfer: closing at kernel
            # return would record ~0 for exactly the hot ticks tracing
            # exists to attribute. Recorded by the caller (loop-side);
            # the batch-start stamps parent traced items' child spans.
            span = (span_name, span_start, time.perf_counter() - t_span0,
                    batch_wall, now_mono)
        if lp is not None:
            # sync paid: future resolution is scheduling work again
            lp.set_category("tick_schedule")
        return per_shard, host, span

    # ------------------------------------------------------------------
    # Bulk path (dense keys; the ≥1M msgs/sec route)
    # ------------------------------------------------------------------
    def make_dense_plan(self, grain_class: type, keys: np.ndarray) -> "_DensePlan":
        """Precompute the key→(shard, lane) batch layout for a recurring bulk
        key set (amortizes the argsort across ticks — e.g. every Presence
        heartbeat round touches the same 1M players)."""
        tbl = self.table(grain_class)
        keys = np.asarray(keys)
        M = keys.shape[0]
        n = tbl.n_shards
        if keys.shape[0] and np.unique(keys).shape[0] != keys.shape[0]:
            # duplicate keys in one bulk tick would scatter twice into one
            # row (nondeterministic write order — a silent turn-semantics
            # violation); the per-key path serializes them across ticks
            raise ValueError(
                "call_batch keys must be unique within a tick; route "
                "duplicate-key traffic through VectorRuntime.call")
        shard, slot = tbl.dense_shard_slot(keys)
        order = np.argsort(shard, kind="stable")
        inv = np.empty_like(order)
        inv[order] = np.arange(M)
        counts = np.bincount(shard, minlength=n)
        B = _bucket(int(counts.max()) if M else MIN_BUCKET)
        sorted_shard = shard[order]
        starts = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(counts, out=starts[1:])
        lane_sorted = np.arange(M) - starts[sorted_shard]
        slots_b = np.full((n, B), tbl.sink_slot, dtype=np.int32)
        valid_b = np.zeros((n, B), dtype=bool)
        khash_b = np.zeros((n, B), dtype=np.int32)
        slots_b[sorted_shard, lane_sorted] = slot[order]
        valid_b[sorted_shard, lane_sorted] = True
        khash_b[sorted_shard, lane_sorted] = keys[order] & 0x7FFFFFFF
        identity = bool(M) and keys[0] == 0 and keys[-1] == M - 1 and \
            np.array_equal(keys, np.arange(M))
        return _DensePlan(keys, order, inv, sorted_shard, lane_sorted, B,
                          slots_b, valid_b, khash_b,
                          identity=identity, counts=counts)

    def call_batch(self, grain_class: type, method: str,
                   keys: np.ndarray, args: dict[str, np.ndarray],
                   fresh: np.ndarray | None = None,
                   plan: "_DensePlan | None" = None,
                   device_results: bool = False):
        """Invoke ``method`` on many dense-keyed activations in one tick.

        ``keys``: int array [M] of dense keys (must be ensure_dense'd and
        unique within the call). ``args``: dict of [M, ...] arrays. Returns
        the stacked result pytree with leading axis [M]. Runs synchronously
        (one kernel launch) — the caller IS the tick. Pass a reusable
        ``plan`` from :meth:`make_dense_plan` for recurring key sets.
        """
        tbl = self.table(grain_class)
        m = self.method_of(grain_class, method)
        if m.args_schema is None:
            m.args_schema = {
                k: (np.asarray(v).dtype, np.asarray(v).shape[1:])
                for k, v in args.items()}
        _validate_args(grain_class, method, m.args_schema, args)
        if plan is None:
            plan = self.make_dense_plan(grain_class, keys)
        M = plan.keys.shape[0]
        d_slots, d_khash, d_valid, d_fresh0 = plan.device_operands(tbl._put)
        if fresh is None:
            # auto-activate: keys never touched get initial_state this tick
            fresh = tbl.dense_fresh_mask(plan.keys)
        if fresh is not None:
            d_fresh = tbl._put(
                jnp.asarray(plan.pack(np.asarray(fresh), bool, ())))
            tbl.mark_dense_active(plan.keys)
        else:
            d_fresh = d_fresh0
        args_b = {}
        for fname, (dtype, shape) in m.args_schema.items():
            args_b[fname] = tbl._put(
                jnp.asarray(plan.pack(np.asarray(args[fname]), dtype, shape)))
        kern = self._kernel(grain_class, method, plan.B,
                            contiguous=self._plan_contiguous(tbl, plan))
        led = self.ledger
        t_led = time.perf_counter() if led is not None else 0.0
        # tick fence: the bulk path is its own tick on the CALLER's
        # thread — it must not read (or commit over) tbl.state while an
        # off-loop worker batch has it donated mid-dispatch
        with self._fence:
            new_state, results = kern(
                tbl.state, d_slots, d_khash, d_fresh, d_valid, args_b)
            if not m.read_only:
                tbl.state = new_state
                self._mark_dirty(grain_class, plan.keys)
        if self.track_load:
            tbl.record_hits(d_slots, d_valid)
        if led is not None:
            # bulk ticks charge dispatch wall (loop-side, synchronous
            # caller) with no per-key labels — labeling a 1M-key bulk
            # tick would cost more than the tick; per-key detail for the
            # bulk regime lives in the on-device per-slot cost twin
            wall = max(0.0, time.perf_counter() - t_led)
            led.charge_tick((grain_class.__name__, method, M, wall, ()))
            if self.track_cost:
                tbl.record_cost(d_slots, d_valid, int(wall * 1e6))
        self.ticks += 1
        self.messages_processed += M
        if device_results:
            # async path: raw [n, B, ...] device results, no host sync —
            # use plan.unpack(...) to materialize caller-order rows later
            return results
        return plan.unpack(results)

    def call_batch_rounds(self, grain_class: type, method: str,
                          keys: np.ndarray,
                          args_rounds: dict[str, np.ndarray],
                          plan: "_DensePlan | None" = None,
                          device_results: bool = False):
        """Sustained-streaming dispatch: K message rounds to the same dense
        key set in ONE kernel call (``lax.scan`` over ticks on device).

        ``args_rounds``: dict of [K, M, ...] arrays — K sequential rounds.
        Turn semantics hold: round k+1 sees the state written by round k
        (ticks are sequential inside the scan). One payload upload + one
        dispatch per K·M messages — the streaming-gateway hot path (the
        PersistentStreamPullingAgent pump re-expressed as a scanned kernel,
        PersistentStreamPullingAgent.cs:141,350-368).
        """
        tbl = self.table(grain_class)
        m = self.method_of(grain_class, method)
        if not args_rounds:
            raise TypeError(
                "call_batch_rounds requires at least one [K, M, ...] args "
                "array to define K; use call_batch for single no-arg ticks")
        if m.args_schema is None:
            m.args_schema = {
                k: (np.asarray(v).dtype, np.asarray(v).shape[2:])
                for k, v in args_rounds.items()}
        _validate_args(grain_class, method, m.args_schema, args_rounds)
        if plan is None:
            plan = self.make_dense_plan(grain_class, keys)
        K = next(iter(args_rounds.values())).shape[0]
        M = plan.keys.shape[0]
        fresh0 = tbl.dense_fresh_mask(plan.keys)
        d_slots, d_khash, d_valid, d_zeros = plan.device_operands(tbl._put)
        if fresh0 is not None:
            d_fresh = tbl._put(
                jnp.asarray(plan.pack(np.asarray(fresh0), bool, ())))
            tbl.mark_dense_active(plan.keys)
        else:
            d_fresh = d_zeros
        args_b = {}
        for fname, (dtype, shape) in m.args_schema.items():
            a = args_rounds[fname]
            if isinstance(a, jax.Array) and plan.identity \
                    and (M == tbl.n_shards * plan.B
                         or tbl.n_shards == 1):
                # DEVICE-resident staged payload on an identity plan: the
                # [K, M, ...] → [K, n, B, ...] layout is a reshape (plus
                # an on-device zero-pad to the bucket size when single-
                # shard), so keep it on device. The host path below would
                # round-trip the whole payload through the tunnel
                # (device→host gather + repack + re-upload — seconds per
                # launch at 1 MB/round), which is what the streaming hot
                # path exists to avoid
                a2 = a.astype(dtype)
                pad = tbl.n_shards * plan.B - M
                if pad:
                    a2 = jnp.pad(
                        a2, ((0, 0), (0, pad)) + ((0, 0),) * len(shape))
                args_b[fname] = tbl._put_rounds(
                    a2.reshape(K, tbl.n_shards, plan.B, *shape))
                continue
            a = np.asarray(a)
            packed = np.stack([plan.pack(a[k], dtype, shape)
                               for k in range(K)])
            args_b[fname] = tbl._put_rounds(jnp.asarray(packed))
        kern = self._scan_kernel(
            grain_class, method, plan.B, K,
            contiguous=self._plan_contiguous(tbl, plan),
            # static select-elision is ONLY safe when every lane is real:
            # a padded lane in contiguous mode addresses by position, and
            # an unmasked write there could corrupt a hashed activation's
            # slot beyond the dense range
            all_valid=bool(plan.valid_b.all()))
        led = self.ledger
        t_led = time.perf_counter() if led is not None else 0.0
        with self._fence:  # see call_batch: bulk ticks serialize with
            # the off-loop worker's donated in-flight batches
            new_state, results = kern(
                tbl.state, d_slots, d_khash, d_fresh, d_valid, args_b)
            if not m.read_only:
                tbl.state = new_state
                self._mark_dirty(grain_class, plan.keys)
        if self.track_load:
            tbl.record_hits(d_slots, d_valid, scale=K)
        if led is not None:
            # the wall already spans all K scanned rounds, so the µs
            # charge needs no scale=K (unlike the per-round hit counts)
            wall = max(0.0, time.perf_counter() - t_led)
            led.charge_tick(
                (grain_class.__name__, method, K * M, wall / max(1, K),
                 ()))
            if self.track_cost:
                tbl.record_cost(d_slots, d_valid, int(wall * 1e6))
        self.ticks += K
        self.messages_processed += K * M
        if device_results:
            return results  # [K, n, B, ...]
        return jax.tree_util.tree_map(
            lambda a: np.stack([plan.unpack(a[k]) for k in range(K)]),
            results)

    def _scan_kernel(self, cls: type, method: str, B: int, K: int,
                     contiguous: bool = False, all_valid: bool = False):
        tbl = self.tables[cls]
        key = ("scan", cls, method, B, K, tbl.capacity, tbl.n_shards,
               contiguous, self.scan_unroll, all_valid)
        k = self._kernel_cache.get(key)
        if k is None:
            k = self._build_kernel(cls, method, scan_rounds=K,
                                   contiguous=contiguous,
                                   scan_all_valid=all_valid)
            self._kernel_cache[key] = k
        return k

    def _plan_contiguous(self, tbl, plan: "_DensePlan") -> bool:
        """Identity plans touch slots [0, counts[s]) per shard in lane
        order — the gather/scatter degenerates to a contiguous slice of the
        slot pool (the 1M-actor bulk regime; ~1000x cheaper on TPU than a
        dynamic 1M-row gather)."""
        return plan.identity and plan.B <= tbl.capacity

    def call_batch_device(self, grain_class: type, method: str,
                          slots_b, khash_b, fresh_b, valid_b, args_b):
        """Zero-copy tick for callers that already hold device-layout
        [n_shards, B] batches (the transport layer / benchmarks). Returns
        the raw [n_shards, B, ...] result pytree without host transfer."""
        tbl = self.table(grain_class)
        m = self.method_of(grain_class, method)
        B = slots_b.shape[1]
        led = self.ledger
        t_led = time.perf_counter() if led is not None else 0.0
        with self._fence:  # see call_batch: serialize with off-loop ticks
            new_state, results = self._kernel(grain_class, method, B)(
                tbl.state, slots_b, khash_b, fresh_b, valid_b, args_b)
            if not m.read_only:
                tbl.state = new_state
        if self.track_load:
            # device-resident masks fold without a host sync — the
            # telemetry stays all-device exactly like the exchange flow
            tbl.record_hits(slots_b, valid_b)
        if led is not None:
            # rows = all lanes (a device-resident valid mask must not be
            # host-synced just to count); per-slot precision comes from
            # record_cost, whose masked scatter stays all-device too
            wall = max(0.0, time.perf_counter() - t_led)
            led.charge_tick(
                (grain_class.__name__, method, int(slots_b.shape[0] * B),
                 wall, ()))
            if self.track_cost:
                tbl.record_cost(slots_b, valid_b, int(wall * 1e6))
        self.ticks += 1
        if isinstance(valid_b, np.ndarray):
            self.messages_processed += int(valid_b.sum())
        else:
            # valid mask lives on device (exchange flows): counting it
            # would force a sync — track lanes separately so
            # messages_processed stays an honest delivered count
            self.exchange_lanes += int(valid_b.shape[0] * B)
        return results

    # ------------------------------------------------------------------
    # Device-tier actor→actor messaging (the ICI fabric as an engine API)
    # ------------------------------------------------------------------
    def route(self, dest_class: type, dest_keys, payload: dict, valid,
              capacity: int = 256, sparse: bool = False):
        """Route per-message payloads to the shards owning ``dest_keys``
        over the tick exchange (ONE all_to_all on the silo axis —
        parallel.transport; the reference's silo-to-silo TCP fabric,
        SURVEY §2.4 "Point-to-point messaging backend").

        dest_keys/valid: [n_shards, B] device arrays (dense keys of
        ``dest_class``); payload: dict of [n_shards, B, ...]. Returns
        (recv_keys, recv_payload, recv_valid, drops) with recv lanes
        [n_shards, n_shards*capacity]. Overflow beyond ``capacity`` lanes
        per (src, dst) pair is dropped and counted (overload shedding —
        the host re-routes next tick).

        ``sparse=True``: dest_keys is a ``(keys_lo, keys_hi)`` int32 pair
        (62-bit uniform hashes split via ops.hash_probe.split64) and the
        owning shard is resolved ON DEVICE through the table's
        DeviceDirectory64 — the on-chip directory tier in the routing
        path (AdaptiveGrainDirectoryCache.cs:178). Unregistered keys are
        routed invalid (dropped + countable by the caller).
        """
        from ..parallel.transport import build_exchange

        if "__key__" in payload:
            raise ValueError("payload field name '__key__' is reserved")
        tbl = self.table(dest_class)
        key = ("exchange", tbl.n_shards, capacity)
        ex = self._kernel_cache.get(key)
        if ex is None:
            ex = build_exchange(self.mesh, capacity=capacity)
            self._kernel_cache[key] = ex
        if sparse:
            from ..ops.hash_probe import device_lookup64
            from .table import _LOC_STRIDE
            keys_lo, keys_hi = dest_keys
            tk_lo, tk_hi, tv = tbl.device_dir.device_arrays()
            loc, found = device_lookup64(
                tk_lo, tk_hi, tv,
                keys_lo.reshape(-1), keys_hi.reshape(-1),
                tbl.device_dir.max_probes)
            loc = loc.reshape(keys_lo.shape)
            found = found.reshape(keys_lo.shape)
            dest_shard = (loc // _LOC_STRIDE).astype(jnp.int32)
            routable = valid & found
            recv, recv_valid, drops = ex(
                dest_shard, routable,
                {"__key__": keys_lo, "__key_hi__": keys_hi, **payload})
            # unregistered destinations count as drops per source shard
            # (the caller's re-route/shed accounting), like overflow
            drops = drops + jnp.sum(valid & ~found, axis=-1)
            recv_lo = recv.pop("__key__")
            recv_hi = recv.pop("__key_hi__")
            return (recv_lo, recv_hi), recv, recv_valid, drops
        per = max(tbl.dense_per_shard, 1)
        dest_shard = (dest_keys // per).astype(jnp.int32)
        recv, recv_valid, drops = ex(
            dest_shard, valid, {"__key__": dest_keys, **payload})
        recv_keys = recv.pop("__key__")
        return recv_keys, recv, recv_valid, drops

    def apply_received(self, dest_class: type, method: str, recv_keys,
                       recv_valid, args: dict, sparse: bool = False):
        """Apply routed messages as invocations on ``dest_class`` — the
        receive half of a cross-shard actor call, entirely on device.

        Turn semantics under fan-in: at most one message per actor per
        tick. Duplicate same-actor deliveries within this batch are masked
        off ON DEVICE (first occurrence wins — deterministic lane order)
        and reported in the returned ``applied`` mask so the caller can
        re-route them next tick (the mailbox-defer analog). Requires the
        dest table's dense regime (keys pre-provisioned + activated; use
        fan-in reductions — ops.segment_sum — for aggregation patterns
        instead of high-duplication apply).

        Returns (results, applied): results [n_shards, L, ...] per-lane
        method results (junk on unapplied lanes), applied [n_shards, L].

        Write-behind dirty tracking does NOT see exchange-applied writes
        (the applied keys live on device; syncing them to host every tick
        would defeat the all-device pipeline) — device-resident message
        flows should persist via scheduled table checkpoints
        (``add_vector_grains(checkpoint_dir=...)``) instead.
        """
        tbl = self.table(dest_class)
        self.method_of(dest_class, method)  # validate the method exists

        if sparse:
            recv_lo, recv_hi = recv_keys
            tk_lo, tk_hi, tv = tbl.device_dir.device_arrays()
            slots, applied, khash = self._apply_resolver(
                dest_class, True)(recv_lo, recv_hi, recv_valid,
                                  tk_lo, tk_hi, tv)
            fresh = jnp.zeros_like(applied)
            results = self.call_batch_device(dest_class, method, slots,
                                             khash, fresh, applied, args)
            return results, applied

        slots, applied, khash = self._apply_resolver(dest_class, False)(
            recv_keys, recv_valid)
        fresh = jnp.zeros_like(applied)
        results = self.call_batch_device(dest_class, method, slots, khash,
                                         fresh, applied, args)
        return results, applied

    def _apply_resolver(self, dest_class: type, sparse: bool):
        """The cached jitted slot-resolution half of
        :meth:`apply_received` (key → local slot + first-delivery dedup
        mask). Cached per (class, regime, capacity, shard layout): a
        fresh ``jax.jit(local)`` per call would RETRACE on every
        delivery round — the repeated-fan-out hot path
        (broadcast_actors' dedup rounds) pays a full compile per round
        without this."""
        from ..ops.route import rank_dense_keys

        tbl = self.table(dest_class)
        per = max(tbl.dense_per_shard, 1)
        key = ("apply", dest_class, sparse, per, tbl.capacity,
               tbl.n_shards,
               tbl.device_dir.max_probes if sparse else 0)
        cached = self._kernel_cache.get(key)
        if cached is not None:
            return cached
        capacity = tbl.capacity
        n_shards = tbl.n_shards

        if sparse:
            from ..ops.hash_probe import device_lookup64
            from .table import _LOC_STRIDE
            probes = tbl.device_dir.max_probes

            def local(klo, khi, ok, dlo, dhi, dv):
                lo, hi, v = klo[0], khi[0], ok[0]
                loc, found = device_lookup64(dlo, dhi, dv, lo, hi, probes)
                if n_shards > 1:
                    myshard = jax.lax.axis_index(SILO_AXIS)
                else:
                    myshard = 0
                # defensive: a lane misrouted against a stale directory
                # must not scribble another actor's slot on this shard
                v = v & found & ((loc // _LOC_STRIDE) == myshard)
                slot = jnp.where(v, loc % _LOC_STRIDE, capacity)
                first = rank_dense_keys(jnp.where(v, slot,
                                                  capacity + 1)) == 0
                applied = v & first
                slot = jnp.where(applied, slot, capacity)
                return slot[None], applied[None], lo[None]

            if n_shards > 1:
                spec = P(SILO_AXIS)
                local = shard_map_compat(
                    local, mesh=self.mesh,
                    in_specs=(spec, spec, spec, P(), P(), P()),
                    out_specs=(spec, spec, spec), check_vma=False)
        else:
            def local(keys, ok):
                k, v = keys[0], ok[0]
                slot = jnp.where(v, k % per, capacity)
                # dedup: only the first delivery per actor applies this
                # tick
                first = rank_dense_keys(jnp.where(v, slot,
                                                  capacity + 1)) == 0
                applied = v & first
                slot = jnp.where(applied, slot, capacity)
                return slot[None], applied[None], \
                    (k & 0x7FFFFFFF).astype(jnp.int32)[None]

            if n_shards > 1:
                spec = P(SILO_AXIS)
                local = shard_map_compat(
                    local, mesh=self.mesh, in_specs=(spec, spec),
                    out_specs=(spec, spec, spec), check_vma=False)
        cached = jax.jit(local)
        self._kernel_cache[key] = cached
        return cached

    # ------------------------------------------------------------------
    # Bulk-population collectives (MapReduce over actors — ROADMAP's
    # DrJAX direction, arXiv 2403.07128): population-wide fan-out/fan-in
    # compiled onto the sharded table as single-dispatch ticks instead of
    # message-per-edge RPC trains. All three primitives serialize with
    # the off-loop tick worker through the PR-9 fence, re-resolve key
    # locations per round (so grow/migration/checkpoint interleaving at
    # their await points is safe by construction), and defer keys that
    # have in-flight per-key turns exactly like call_group conflicts
    # defer (turn semantics: at most one message per activation per
    # tick, bulk or not).
    # ------------------------------------------------------------------
    def _bulk_resolve(self, cls: type, keys: np.ndarray | None
                      ) -> tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray]:
        """Resolve a bulk target set into ``(keys, shard, slot, fresh)``
        numpy arrays. ``keys=None`` targets every LIVE activation (dense
        keys actually touched + resident hashed rows — the "apply to the
        whole population" form). An explicit key subset may include
        dense-provisioned keys not yet activated (they fresh-init this
        tick, the call_batch auto-activate contract); hashed keys must
        be resident — non-resident ones are skipped, mirroring the
        live-actor semantics (the returned keys array is the applied
        set). Locations are resolved HERE, per call: bulk rounds never
        cache a (shard, slot) across an await, so a migration or grow
        between rounds can never strand a stale address."""
        tbl = self.table(cls)
        if keys is None:
            dense = np.flatnonzero(tbl.dense_active).astype(np.int64)
            n_h = len(tbl.key_to_slot)
            hashed = np.fromiter(tbl.key_to_slot, dtype=np.int64,
                                 count=n_h)
            fresh = np.zeros(dense.size + n_h, dtype=bool)
        else:
            # np.unique deduplicates: one message per actor per bulk op
            keys = np.unique(np.asarray(keys, dtype=np.int64))
            is_dense = (keys >= 0) & (keys < tbl.dense_n)
            dense = keys[is_dense]
            resident = np.fromiter(
                (k in tbl.key_to_slot for k in keys[~is_dense].tolist()),
                dtype=bool, count=int((~is_dense).sum()))
            hashed = keys[~is_dense][resident]
            fresh = np.concatenate([
                ~tbl.dense_active[dense] if dense.size else
                np.zeros(0, bool),
                np.zeros(hashed.size, bool)])
        d_sh, d_sl = tbl.dense_shard_slot(dense)
        d_shard, d_slot = d_sh.astype(np.int32), d_sl.astype(np.int32)
        if hashed.size:
            locs = np.array([tbl.key_to_slot[int(k)] for k in hashed],
                            dtype=np.int32).reshape(-1, 2)
            h_shard, h_slot = locs[:, 0], locs[:, 1]
        else:
            h_shard = h_slot = np.zeros(0, dtype=np.int32)
        out_keys = np.concatenate([dense, hashed]) if hashed.size \
            else dense
        shard = np.concatenate([d_shard, h_shard])
        slot = np.concatenate([d_slot, h_slot])
        return out_keys, shard, slot, fresh

    def _bulk_pack(self, tbl, shard: np.ndarray, slot: np.ndarray,
                   keys: np.ndarray, fresh: np.ndarray):
        """Arbitrary-location analog of ``make_dense_plan``'s layout:
        group M (shard, slot) targets into padded ``[n_shards, B]``
        batch buffers (idle lanes aim at the sink row)."""
        n = tbl.n_shards
        order = np.argsort(shard, kind="stable")
        counts = np.bincount(shard, minlength=n)
        B = _bucket(int(counts.max()) if shard.size else MIN_BUCKET)
        starts = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(counts, out=starts[1:])
        ss = shard[order]
        lane = np.arange(shard.size) - starts[ss]
        slots_b = np.full((n, B), tbl.sink_slot, dtype=np.int32)
        valid_b = np.zeros((n, B), dtype=bool)
        khash_b = np.zeros((n, B), dtype=np.int32)
        fresh_b = np.zeros((n, B), dtype=bool)
        slots_b[ss, lane] = slot[order]
        valid_b[ss, lane] = True
        khash_b[ss, lane] = (keys[order] & 0x7FFFFFFF).astype(np.int32)
        fresh_b[ss, lane] = fresh[order]
        return slots_b, khash_b, fresh_b, valid_b, B

    def _bulk_args(self, cls: type, m, kwargs: dict | None, n: int,
                   B: int) -> dict:
        """Broadcast ONE kwargs row to every lane of a ``[n, B]`` batch
        (the map/reduce payload form: same message to the whole
        population; per-actor payloads are call_batch's job)."""
        kwargs = kwargs or {}
        if m.args_schema is None:
            m.args_schema = {
                k: (np.asarray(v).dtype, np.asarray(v).shape)
                for k, v in kwargs.items()}
        _validate_args(cls, m.name, m.args_schema, kwargs)
        return {f: np.broadcast_to(
                    np.asarray(kwargs[f], dtype=dtype), (n, B, *shape))
                for f, (dtype, shape) in m.args_schema.items()}

    def _bulk_apply_once(self, cls: type, method: str, keys: np.ndarray,
                         shard: np.ndarray, slot: np.ndarray,
                         fresh: np.ndarray, kwargs: dict | None):
        """One bulk tick over resolved targets: pack → kernel → commit,
        under the tick fence (the caller IS the tick, like call_batch).
        Returns ``(results_device, valid_b)`` for the reduce half."""
        tbl = self.table(cls)
        m = self.method_of(cls, method)
        slots_b, khash_b, fresh_b, valid_b, B = self._bulk_pack(
            tbl, shard, slot, keys, fresh)
        args_b = self._bulk_args(cls, m, kwargs, tbl.n_shards, B)
        # the fence/kernel/commit/telemetry block is call_batch_device's
        # (one tick-semantics implementation, not two that drift); this
        # wrapper only adds the host-side bulk bookkeeping it can do
        # because it HOLDS the keys: write-behind dirty marks and dense
        # activation
        results = self.call_batch_device(
            cls, method, slots_b,
            jnp.asarray(khash_b), jnp.asarray(fresh_b), valid_b,
            {k: jnp.asarray(v) for k, v in args_b.items()})
        if not m.read_only:
            self._mark_dirty(cls, keys)
            if fresh.any():
                # read-only bulk ticks never write the fresh-init rows
                # back (the kernel skips the scatter), so marking those
                # keys active would hand later writes an uninitialized
                # row; the fresh mask just re-derives next call —
                # idempotent reads
                tbl.mark_dense_active(keys[fresh])
        return results, valid_b

    def _busy_split(self, cls: type, keys: np.ndarray):
        """Split targets into ``(ready, deferred, busy_mask)`` against
        keys with queued or worker-in-flight per-key turns — the bulk
        analog of ``_claim``'s same-slot conflict defer. ``busy_mask``
        is None when nothing is busy (the common case — callers use it
        to slice parallel arrays without recomputing the membership
        test)."""
        busy = self.pending_key_hashes(cls)
        if not busy:
            return keys, keys[:0], None
        mask = np.isin(keys, np.fromiter(busy, dtype=np.int64,
                                         count=len(busy)))
        return keys[~mask], keys[mask], mask

    async def _bulk_yield(self) -> None:
        """Let deferred per-key turns drain one round: run the pending
        tick (or await the off-loop worker's quiescence) before the next
        bulk round re-resolves."""
        if self.pending:
            self._tick()
        if self._inflight:
            await self._quiesced.wait()
        else:
            await asyncio.sleep(0)

    async def _bulk_rounds(self, grain_class: type, method: str,
                           kwargs: dict | None, keys, skip_busy: bool,
                           on_apply) -> None:
        """The ONE deferral-round driver behind map_actors and
        reduce_actors: resolve targets → split off keys with queued/
        in-flight per-key turns (unless ``skip_busy`` — read-only
        reductions have no turn to conflict with) → bulk-apply the
        ready slice → yield a tick round for the deferred rest and
        re-resolve. ``on_apply(results, valid_b, n_ready)`` accumulates
        per round. Shared so the conflict/selection logic cannot drift
        between the two primitives."""
        target_keys = keys
        while True:
            ks, shard, slot, fresh = self._bulk_resolve(grain_class,
                                                        target_keys)
            if skip_busy:
                ready, deferred, bmask = ks, ks[:0], None
            else:
                ready, deferred, bmask = self._busy_split(grain_class,
                                                          ks)
            if ready.size:
                sel = slice(None) if bmask is None else ~bmask
                results, valid_b = self._bulk_apply_once(
                    grain_class, method, ks[sel], shard[sel], slot[sel],
                    fresh[sel], kwargs)
                on_apply(results, valid_b, int(ready.size))
            if not deferred.size:
                return
            target_keys = deferred
            await self._bulk_yield()

    async def map_actors(self, grain_class: type, method: str,
                         kwargs: dict | None = None,
                         keys: np.ndarray | None = None) -> int:
        """Apply ``method`` (one broadcast kwargs row) to every live
        activation of ``grain_class`` — or a key subset — as bulk ticks:
        ONE kernel dispatch per conflict-free round instead of N per-key
        messages. Keys with in-flight per-key turns defer to later
        rounds (call_group conflict semantics); locations re-resolve per
        round, so migration/grow/checkpoint racing the await points stay
        safe under the tick fence. Returns the number of activations
        applied."""
        m = self.method_of(grain_class, method)
        if m.args_schema is not None:
            # validate up front: a schema mismatch must fail even when
            # the live population is empty (no batch ever runs)
            _validate_args(grain_class, method, m.args_schema,
                           kwargs or {})
        applied = 0

        def on_apply(_results, _valid_b, n: int) -> None:
            nonlocal applied
            applied += n

        await self._bulk_rounds(grain_class, method, kwargs, keys,
                                False, on_apply)
        return applied

    async def reduce_actors(self, grain_class: type, method: str,
                            kwargs: dict | None = None,
                            keys: np.ndarray | None = None,
                            combine: str = "sum"):
        """Run ``method`` over the population and reduce the per-actor
        results ON DEVICE (ops.segment_reduce.masked_reduce): ONE
        scalar/row crosses the host boundary instead of N responses.
        ``combine``: "sum" | "max" | "min" | "mean" (mean = sum/count,
        combined exactly across rounds and silos as (sum, count) pairs).
        Returns the reduced result pytree (host numpy); None when no
        live actor matched."""
        value, count = await self.reduce_actors_partial(
            grain_class, method, kwargs, keys, combine)
        if value is None or count == 0:
            return None
        if combine == "mean":
            return jax.tree_util.tree_map(lambda v: v / count, value)
        return value

    async def reduce_actors_partial(self, grain_class: type, method: str,
                                    kwargs: dict | None = None,
                                    keys: np.ndarray | None = None,
                                    combine: str = "sum"):
        """The combinable form of :meth:`reduce_actors`: returns
        ``(partial_value, count)`` where mean partials carry the SUM
        (divide at the top) — what the dispatcher's cross-silo merge
        folds, and what multi-round conflict deferral folds locally."""
        from ..ops.segment_reduce import (REDUCE_OPS, host_fold,
                                          masked_reduce)
        op = "sum" if combine == "mean" else combine
        if op not in REDUCE_OPS:
            raise ValueError(
                f"combine must be one of {REDUCE_OPS + ('mean',)}, "
                f"got {combine!r}")
        m = self.method_of(grain_class, method)
        if m.args_schema is not None:
            _validate_args(grain_class, method, m.args_schema,
                           kwargs or {})  # fail fast on empty tables too
        total = None
        count = 0
        fold = host_fold(op)

        def on_apply(results, valid_b, n: int) -> None:
            nonlocal total, count
            part = jax.tree_util.tree_map(
                np.asarray,
                masked_reduce(results, jnp.asarray(valid_b), op=op))
            count += n
            total = part if total is None else \
                jax.tree_util.tree_map(fold, total, part)

        # read-only reductions never write, so there is no turn to
        # conflict with — they run in one tick over everything
        await self._bulk_rounds(grain_class, method, kwargs, keys,
                                m.read_only, on_apply)
        return total, count

    def _init_kernel(self, cls: type, B: int):
        """Bulk OnActivate kernel: scatter ``initial_state(khash)`` rows
        at masked lanes — no handler, so it serves read-only methods
        too. Cached per (class, B, capacity, shards) like the tick
        kernels."""
        tbl = self.tables[cls]
        key = ("bulkinit", cls, B, tbl.capacity, tbl.n_shards)
        k = self._kernel_cache.get(key)
        if k is not None:
            return k
        init = cls.initial_state
        mesh = tbl.mesh

        def local(state, slots, khash, fresh):
            state_l = jax.tree_util.tree_map(lambda a: a[0], state)
            slots_l, khash_l, fresh_l = slots[0], khash[0], fresh[0]
            rows = jax.tree_util.tree_map(lambda f: f[slots_l], state_l)
            init_rows = jax.vmap(init)(khash_l)

            def sel(a, b):
                return jnp.where(
                    fresh_l.reshape(fresh_l.shape
                                    + (1,) * (a.ndim - 1)), a, b)

            new_state_l = jax.tree_util.tree_map(
                lambda f, ir, r: f.at[slots_l].set(sel(ir, r)),
                state_l, init_rows, rows)
            return jax.tree_util.tree_map(lambda a: a[None], new_state_l)

        body = local
        if tbl.n_shards > 1:
            spec = P(SILO_AXIS)
            body = shard_map_compat(
                body, mesh=mesh, in_specs=(spec, spec, spec, spec),
                out_specs=spec, check_vma=False)
        k = jax.jit(body, donate_argnums=(0,))
        self._kernel_cache[key] = k
        return k

    def _bulk_activate(self, cls: type, keys: np.ndarray) -> None:
        """Bulk OnActivate for dense keys a broadcast is about to
        scatter into: fresh-init rows land BEFORE apply_received's
        zero-fresh batches touch them (the per-key paths do this one
        activation at a time; bulk fan-out does it as one scatter)."""
        tbl = self.table(cls)
        fresh = tbl.dense_fresh_mask(keys)
        if fresh is None:
            return
        ks = np.unique(keys[fresh])
        sh, sl = tbl.dense_shard_slot(ks)
        shard, slot = sh.astype(np.int32), sl.astype(np.int32)
        slots_b, khash_b, fresh_b, _valid_b, B = self._bulk_pack(
            tbl, shard, slot, ks, np.ones(ks.size, bool))
        kern = self._init_kernel(cls, B)
        with self._fence:
            tbl.state = kern(tbl.state, jnp.asarray(slots_b),
                             jnp.asarray(khash_b), jnp.asarray(fresh_b))
        tbl.mark_dense_active(ks)

    async def broadcast_actors(self, grain_class: type, method: str,
                               targets: np.ndarray,
                               args: dict | None = None,
                               chunk: int = 16384) -> int:
        """Edge-list fan-out as device collectives: deliver ``method``
        to ``targets[i]`` with per-edge payload ``args[f][i]`` — the
        celebrity-post multicast as a handful of batched dispatches
        instead of O(edges) messages. Targets must be dense-regime keys
        (the follower-list case); each host-side chunk rides ONE
        ``parallel.transport`` exchange to the owning shards (capacity
        sized so overflow drops are impossible) and scatters into target
        rows via :meth:`apply_received`, whose on-device dedup gives
        duplicate targets the mailbox-defer semantics across ticks.
        Edge targets with in-flight per-key turns defer to later rounds
        like map_actors. Returns the number of edges delivered."""
        tbl = self.table(grain_class)
        targets = np.asarray(targets, dtype=np.int64).reshape(-1)
        if targets.size and (targets.min() < 0
                             or targets.max() >= tbl.dense_n):
            raise ValueError(
                "broadcast_actors targets must be dense-regime keys "
                f"in [0, {tbl.dense_n}); route hashed-key traffic "
                "through map_actors/call paths")
        m = self.method_of(grain_class, method)
        E = targets.shape[0]
        args = args or {}
        if m.args_schema is None:
            m.args_schema = {
                k: (np.asarray(v).dtype, np.asarray(v).shape[1:]
                    if np.asarray(v).ndim else ())
                for k, v in args.items()}
        schema = m.args_schema
        if set(args) != set(schema):
            _validate_args(grain_class, method, schema, args)
        # per-edge [E, *shape] payloads; scalars broadcast to every edge
        flat_args = {f: np.broadcast_to(
                         np.asarray(args[f], dtype=dtype), (E, *shape))
                     for f, (dtype, shape) in schema.items()}
        delivered = 0
        pending = (targets, flat_args)
        while pending[0].size:
            tg, fa = pending
            _ready, deferred, bmask = self._busy_split(grain_class, tg)
            if deferred.size:
                pending = (tg[bmask],
                           {f: a[bmask] for f, a in fa.items()})
                tg, fa = tg[~bmask], \
                    {f: a[~bmask] for f, a in fa.items()}
            else:
                pending = (tg[:0], {f: a[:0] for f, a in fa.items()})
            for off in range(0, tg.shape[0], chunk):
                if off:
                    # loop fairness between chunk dispatches: a
                    # celebrity-sized edge list is dozens of chunks and
                    # each is a synchronous device call — without a
                    # yield the whole pass blocks the loop past the
                    # membership probe timeout (the gauntlet QoS
                    # failure). One chunk stays the atomic quantum;
                    # chunks execute in order, so stacked item-major
                    # stream batches keep per-key token order.
                    await asyncio.sleep(0)
                ce = tg[off:off + chunk]
                ca = {f: a[off:off + chunk] for f, a in fa.items()}
                delivered += self._broadcast_chunk(grain_class, method,
                                                   ce, ca)
            if not pending[0].size:
                return delivered
            await self._bulk_yield()
        return delivered

    async def stream_fanout(self, grain_class: type, method: str,
                            targets: np.ndarray,
                            args: dict | None = None,
                            chunk: int = 16384) -> int:
        """Device-tier stream delivery entry (streams.device): one
        publish batch's per-subscriber fan-out rides the broadcast
        machinery unchanged — ``_bulk_activate`` fresh-init scatter,
        ``route`` edge exchange, ``apply_received`` dedup rounds, all
        under the tick fence (so grow/migration/checkpoint serialize
        with every delivery round exactly like PR-13 bulk ticks). The
        caller stacks a batch's items item-major, so the dedup rounds'
        first-occurrence-wins lane order IS per-key token order — the
        per-consumer event-order invariant. Returns edge-events
        delivered."""
        targets = np.asarray(targets, dtype=np.int64).reshape(-1)
        d = await self.broadcast_actors(grain_class, method, targets,
                                        args, chunk=chunk)
        self.last_stream_group = int(targets.size)
        if self.stats is not None:
            self.stats.increment("streams.device.fanout_rounds")
        return d

    def _broadcast_chunk(self, cls: type, method: str,
                         targets: np.ndarray, args: dict) -> int:
        """Route one edge chunk to its owning shards (one all_to_all)
        and apply it, re-applying deduped duplicate-target lanes tick by
        tick until every edge lands. Synchronous: the dedup rounds are
        back-to-back device calls (each under the tick fence via
        call_batch_device), so no per-key turn can interleave
        mid-chunk."""
        tbl = self.table(cls)
        self._bulk_activate(cls, targets)
        n = tbl.n_shards
        E = targets.shape[0]
        if E == 0:
            return 0
        schema = tbl.methods[method].args_schema
        if n == 1:
            # lane count bucketed to a power of two so partition-size
            # jitter across rounds reuses the same compiled kernels
            B = _bucket(E)
            pad = B - E
            recv_keys = jnp.asarray(np.concatenate(
                [targets, np.zeros(pad, dtype=targets.dtype)])[None, :])
            recv_valid = jnp.asarray(np.concatenate(
                [np.ones(E, bool), np.zeros(pad, bool)])[None, :])
            recv_args = {}
            for f, (dtype, shape) in schema.items():
                a = np.asarray(args[f], dtype=dtype)
                recv_args[f] = jnp.asarray(np.concatenate(
                    [a, np.zeros((pad, *shape), dtype=dtype)])[None])
        else:
            # split edges across source shards (the host is every
            # shard's ingress here), pad to equal POWER-OF-TWO lanes
            # (bucketed so varying edge counts reuse the compiled
            # exchange), capacity = lanes-per-shard so per-(src, dst)
            # overflow is impossible by construction (rank < L <=
            # capacity)
            L = _bucket(-(-E // n))
            pad = n * L - E
            tg = np.concatenate([targets,
                                 np.zeros(pad, dtype=targets.dtype)])
            vd = np.concatenate([np.ones(E, bool), np.zeros(pad, bool)])
            payload = {}
            for f, (dtype, shape) in schema.items():
                a = np.asarray(args[f], dtype=dtype)
                a = np.concatenate(
                    [a, np.zeros((pad, *shape), dtype=dtype)])
                payload[f] = jnp.asarray(a.reshape(n, L, *shape))
            recv_keys, recv_args, recv_valid, drops = self.route(
                cls, jnp.asarray(tg.reshape(n, L)), payload,
                jnp.asarray(vd.reshape(n, L)), capacity=L)
            # capacity == L makes overflow impossible; a nonzero count
            # here means the invariant broke, not load
            assert int(np.asarray(drops).sum()) == 0
        delivered = 0
        valid = recv_valid
        while True:
            _res, applied = self.apply_received(cls, method, recv_keys,
                                                valid, recv_args)
            valid = valid & ~applied
            got = int(np.asarray(jnp.sum(applied)))
            delivered += got
            left = int(np.asarray(jnp.sum(valid)))
            if left == 0 or got == 0:
                # got == 0 with lanes left cannot happen for in-range
                # dense keys (dedup always applies the first of each);
                # the guard keeps a logic bug from spinning forever
                break
        if delivered and not tbl.methods[method].read_only:
            # write-behind dirty marks: apply_received's device-resident
            # exchange exemption does NOT apply here — broadcast holds
            # the target keys on the host, so the flusher must see the
            # written rows or a restart silently reverts every
            # broadcast-applied update
            self._mark_dirty(cls, np.unique(targets))
        return delivered

    async def join_when(self, grain_class: type, keys: np.ndarray,
                        k: int | None = None, *, method: str,
                        kwargs: dict | None = None,
                        timeout: float | None = None,
                        poll: float = 0.02) -> int:
        """Join-calculus readiness step (arXiv 1302.6329 direction):
        resolve when at least ``k`` of ``keys`` (default: all) report
        ready through ``method`` — a read-only actor method returning
        0/1 per actor. Each poll is ONE reduce_actors sum (a single
        device reduction, one scalar to host) instead of K host futures
        bouncing through the loop. Returns the ready count observed."""
        keys = np.asarray(keys, dtype=np.int64)
        need = int(keys.size if k is None else k)
        return await join_poll(
            lambda: self.reduce_actors(grain_class, method, kwargs,
                                       keys=keys, combine="sum"),
            need, timeout, poll)

    # ------------------------------------------------------------------
    # Kernel construction
    # ------------------------------------------------------------------
    def _kernel(self, cls: type, method: str, B: int,
                contiguous: bool = False, donate_operands: bool = False):
        tbl = self.tables[cls]
        key = (cls, method, B, tbl.capacity, tbl.n_shards, contiguous,
               donate_operands)
        k = self._kernel_cache.get(key)
        if k is None:
            k = self._build_kernel(cls, method, contiguous=contiguous,
                                   donate_operands=donate_operands)
            self._kernel_cache[key] = k
            if donate_operands:
                # first invocation compiles, and compiling an operand-
                # donating kernel emits a known-benign UserWarning for
                # buffers XLA cannot alias (the bool masks always;
                # slots/khash when no same-shape output remains —
                # donation stays correct, they just aren't aliased).
                # Suppress it for THAT call only: the cache holds the
                # raw kernel, so steady-state ticks never touch the
                # process warnings filter, and application JAX code
                # keeps the diagnostic for its own kernels.
                raw = k

                def k(*a, _raw=raw):
                    with warnings.catch_warnings():
                        warnings.filterwarnings(
                            "ignore",
                            message="Some donated buffers were not usable")
                        return _raw(*a)
        return k

    def _build_kernel(self, cls: type, method: str, scan_rounds: int = 0,
                      contiguous: bool = False,
                      scan_all_valid: bool = False,
                      donate_operands: bool = False):
        tbl = self.tables[cls]
        m = tbl.methods[method]
        handler = m.fn
        init = cls.initial_state
        mesh = tbl.mesh
        read_only = m.read_only

        def make_access(slots_l):
            """(read, write_at) for this tick's slot addressing. The
            contiguous variant replaces the dynamic gather/scatter with
            static slices of the slot pool (identity plans: lane i ==
            slot i; ~1000x cheaper than a 1M-row gather on TPU)."""
            B = slots_l.shape[0]
            if contiguous:
                return (lambda f: f[:B]), \
                    (lambda f, v: f.at[:B].set(v))
            return (lambda f: f[slots_l]), \
                (lambda f, v: f.at[slots_l].set(v))

        def sel(mask, a, b):
            return jnp.where(
                mask.reshape(mask.shape + (1,) * (a.ndim - 1)), a, b)

        def local_step(state, slots, khash, fresh, valid, args):
            # block shapes: state [1, C+1, ...]; slots/khash/fresh/valid
            # [1, B]; args [1, B, ...] — squeeze the shard-block axis
            state_l = jax.tree_util.tree_map(lambda a: a[0], state)
            slots_l, khash_l = slots[0], khash[0]
            fresh_l, valid_l = fresh[0], valid[0]
            args_l = jax.tree_util.tree_map(lambda a: a[0], args)
            read, write_at = make_access(slots_l)

            rows = jax.tree_util.tree_map(read, state_l)
            init_rows = jax.vmap(init)(khash_l)
            rows = jax.tree_util.tree_map(
                lambda ir, r: sel(fresh_l, ir, r), init_rows, rows)
            new_rows, results = jax.vmap(handler)(rows, args_l)
            if read_only:
                out_state = state
            else:
                write = valid_l
                new_state_l = jax.tree_util.tree_map(
                    lambda f, nr, r: write_at(f, sel(write, nr, r)),
                    state_l, new_rows, rows)
                out_state = jax.tree_util.tree_map(
                    lambda a: a[None], new_state_l)
            return out_state, jax.tree_util.tree_map(
                lambda a: a[None], results)

        if scan_rounds:
            import jax.lax as lax

            def init_pass(state, slots, khash, fresh, valid):
                # fresh-init BEFORE the scan: the OnActivate pre-pass, so
                # round 0 of the scan sees initialized rows and later rounds
                # never re-init
                st = jax.tree_util.tree_map(lambda a: a[0], state)
                slots_l, khash_l = slots[0], khash[0]
                write = fresh[0] & valid[0]
                read, write_at = make_access(slots_l)
                rows = jax.tree_util.tree_map(read, st)
                init_rows = jax.vmap(init)(khash_l)
                new_st = jax.tree_util.tree_map(
                    lambda f, ir, r: write_at(f, sel(write, ir, r)),
                    st, init_rows, rows)
                return jax.tree_util.tree_map(lambda a: a[None], new_st)

            def scan_step(carry, slots, valid, args_k):
                """The per-round scan body, statically specialized: the
                init pass already ran, so fresh-init is GONE by
                construction (not a runtime-zero mask the simplifier
                must fold), and when the plan covers every lane
                (scan_all_valid) the per-field validity select — a full
                extra read+where of each state field per round, a
                measurable slice of the MXU-handler engine tax — is
                dropped statically too."""
                state_l = jax.tree_util.tree_map(lambda a: a[0], carry)
                slots_l = slots[0]
                args_l = jax.tree_util.tree_map(lambda a: a[0], args_k)
                read, write_at = make_access(slots_l)
                rows = jax.tree_util.tree_map(read, state_l)
                new_rows, results = jax.vmap(handler)(rows, args_l)
                if read_only:
                    out_state = carry
                else:
                    if scan_all_valid:
                        new_state_l = jax.tree_util.tree_map(
                            write_at, state_l, new_rows)
                    else:
                        valid_l = valid[0]
                        new_state_l = jax.tree_util.tree_map(
                            lambda f, nr, r: write_at(
                                f, sel(valid_l, nr, r)),
                            state_l, new_rows, rows)
                    out_state = jax.tree_util.tree_map(
                        lambda a: a[None], new_state_l)
                return out_state, jax.tree_util.tree_map(
                    lambda a: a[None], results)

            def scanned(state, slots, khash, fresh, valid, args_rounds):
                # args_rounds leaves: [K, n, B, ...] — scan over K ticks;
                # tick k+1 reads the state tick k wrote (serial turns)
                state = init_pass(state, slots, khash, fresh, valid)

                def one(carry, args_k):
                    return scan_step(carry, slots, valid, args_k)
                return lax.scan(one, state, args_rounds,
                                unroll=max(1, self.scan_unroll))

            body = scanned
        else:
            body = local_step

        if tbl.n_shards > 1:
            spec = P(SILO_AXIS)
            pspec = P(None, SILO_AXIS) if scan_rounds else spec
            body = shard_map_compat(
                body, mesh=mesh,
                in_specs=(spec, spec, spec, spec, spec, pspec),
                out_specs=(spec, P(None, SILO_AXIS) if scan_rounds else spec),
                check_vma=False)
        # else: single-shard — shard_map is semantically a no-op but pays a
        # large dispatch penalty (committed shardings); plain jit
        if read_only:
            donate: tuple = ()
        elif donate_operands:
            # per-tick operand buffers (slots/khash/fresh/valid/args) are
            # fresh arrays the caller never reuses — donate them alongside
            # the state so the staging hand-off is zero-copy where XLA can
            # alias and scratch-reuse elsewhere. NEVER set for kernels fed
            # by cached _DensePlan.device_operands (those persist across
            # ticks by design).
            donate = (0, 1, 2, 3, 4, 5)
        else:
            donate = (0,)
        return jax.jit(body, donate_argnums=donate)
