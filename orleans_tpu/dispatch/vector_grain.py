"""VectorGrain: device-tier grains with jax-traceable handlers.

This is the TPU-native inversion of the reference's per-message dispatch
(SURVEY.md §7; /root/reference/src/Orleans.Runtime/Core/Dispatcher.cs hot
path): instead of scheduling one turn per message on a thread, all pending
invocations of one grain class are coalesced each tick into ONE vectorized
actor-update kernel over a slot-table of activation state
(orleans_tpu.dispatch.table/engine). Per-activation single-threaded-turn
semantics hold by construction: a tick applies at most one message per
activation (conflicts defer to the next tick — the mailbox semantics of
``ActivationData.EnqueueMessage``, ActivationData.cs:566).

A VectorGrain declares:
* ``STATE`` — dict of field → (dtype, shape): the activation state row.
* ``initial_state(key_hash)`` — pure fn: int64 scalar → state row pytree
  (on-device activation, the ``OnActivateAsync`` analog fused into the tick).
* handler methods decorated ``@actor_method``: pure
  ``(state_row, args_row) -> (new_state_row, result)`` functions, vmapped
  by the engine. No Python side effects; jnp ops only.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

__all__ = ["VectorGrain", "actor_method", "vector_methods"]


class ActorMethod:
    """Descriptor wrapper marking a jax-traceable handler."""

    def __init__(self, fn: Callable, args_schema: dict | None,
                 read_only: bool):
        self.fn = fn
        self.name = fn.__name__
        # args schema: field → (dtype, shape); inferred from the first call
        # when not declared (declared = better errors + no first-call probe)
        self.args_schema = args_schema
        self.read_only = read_only

    def __get__(self, obj, objtype=None):
        # accessed on the class: return self so the engine can find it
        return self

    def infer_schema(self, args: dict[str, Any]) -> dict:
        if self.args_schema is None:
            self.args_schema = {
                k: (np.asarray(v).dtype, np.asarray(v).shape)
                for k, v in args.items()
            }
        return self.args_schema


def actor_method(fn: Callable | None = None, *, args: dict | None = None,
                 read_only: bool = False):
    """Mark a VectorGrain handler.

    ``@actor_method`` or ``@actor_method(args={"pos": (jnp.float32, (2,))})``.
    ``read_only=True`` handlers skip the state scatter (no write-back) — the
    device analog of ``[ReadOnly]`` interleaving.
    """
    def wrap(f: Callable) -> ActorMethod:
        return ActorMethod(f, args, read_only)
    if fn is not None:
        return wrap(fn)
    return wrap


class VectorGrain:
    """Base marker class for device-tier grains.

    Subclasses are never instantiated: state lives in the silo's
    ShardedActorTable; handlers are static pure functions.
    """

    STATE: dict[str, tuple] = {}

    @staticmethod
    def initial_state(key_hash):  # pragma: no cover — must override
        """key_hash: int64 scalar (GrainId.uniform_hash mod 2^63) → state
        row pytree matching STATE."""
        raise NotImplementedError

    # Idle collection age for table slots (host-driven); None = never.
    COLLECTION_AGE: float | None = None


def vector_methods(cls: type) -> dict[str, ActorMethod]:
    out = {}
    for name in dir(cls):
        v = getattr(cls, name)
        if isinstance(v, ActorMethod):
            out[name] = v
    return out
