"""Device-tier elastic resharding: re-range a dense actor table onto a
mesh with a different shard count — BOTH directions.

Re-design of /root/reference/src/Orleans.Runtime/GrainDirectory/
``GrainDirectoryHandoffManager.cs:1-340``: the reference re-ranges
directory partitions when silos LEAVE (handoff to survivors) and when
silos JOIN (split to the newcomer, join path via
``LocalGrainDirectory.cs:374-383``). On the device tier the partition is
the dense block mapping key → (key // per_shard, key % per_shard), so a
re-range is a snapshot → key-major flatten → block re-partition →
restore: one reshape, no per-key handoff messages — the mesh is the
directory.
"""

from __future__ import annotations

import numpy as np

__all__ = ["reshard_dense"]


def reshard_dense(old_table, new_rt):
    """Re-range ``old_table``'s densely-provisioned keyspace onto
    ``new_rt``'s mesh (grow n→m or shrink m→n; any shard counts) and
    return the new table. State rows carry over exactly; the activation
    bitmap carries too, so rehydrated rows are not re-initialized on
    next touch. The old table is left untouched (the caller retires it —
    or keeps it as the rollback snapshot)."""
    cls = old_table.grain_class
    n_keys = old_table.dense_n
    if n_keys == 0 or old_table.dense_per_shard == 0:
        raise ValueError(
            "reshard_dense re-ranges the dense regime; hashed-key tables "
            "migrate per-key through checkpoint restore (VectorCheckpointer)")
    if old_table.key_to_slot:
        raise ValueError(
            "table mixes hashed keys with the dense range; drain hashed "
            "activations (release) before a dense re-range")
    snap = old_table.snapshot()
    per_old = old_table.dense_per_shard
    n_old = old_table.n_shards

    tbl2 = new_rt.table(cls)
    tbl2.ensure_dense(n_keys)
    per_new = tbl2.dense_per_shard
    m = tbl2.n_shards
    restored = {}
    for name, arr in snap.items():
        # key-major flatten of the old block mapping, truncated to the
        # real keyspace (the old last shard's tail rows are padding)
        km = arr[:, :per_old].reshape(n_old * per_old,
                                      *arr.shape[2:])[:n_keys]
        pad = m * per_new - n_keys
        if pad:
            km = np.concatenate(
                [km, np.zeros((pad, *km.shape[1:]), km.dtype)])
        full = np.zeros((m, tbl2.capacity + 1, *km.shape[1:]), km.dtype)
        full[:, :per_new] = km.reshape(m, per_new, *km.shape[1:])
        restored[name] = full
    tbl2.restore(restored)
    tbl2.dense_active[:] = old_table.dense_active[:n_keys]
    return tbl2
