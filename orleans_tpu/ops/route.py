"""Message routing (outbox pack) without a sort.

The ICI transport packs each tick's outbound messages into per-destination
buckets (``parallel.transport._pack_outbox`` delegates here). The obvious
implementation ranks messages within their destination group via
``argsort`` — but sorts are among the weakest ops on TPU (O(B log^2 B)
sorting networks on the VPU). The rank is really a *prefix count*:

    rank[i] = #{ j < i : dest[j] == dest[i] }  ==  (L @ onehot(dest))[i, dest[i]]

with L the strictly-lower-triangular ones matrix — one [B, B] x [B, S]
matmul on the MXU. The Pallas kernel builds both the L block and the
one-hot block in VMEM from iotas (neither ever touches HBM), so the kernel
reads B int32 ids and writes the [B, S] prefix-count table; XLA's matmul
would have to materialize L (O(B^2)) and onehot (O(B*S)) in HBM first.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["rank_by_dest", "rank_dense_keys", "pack_by_dest"]


def _prefix_kernel(ids_ref, out_ref, *, block: int, n_dest: int):
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # rows of C = messages i; contraction axis = earlier messages j
    @pl.when(j <= i)
    def _():
        row = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0) \
            + i * block
        col = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1) \
            + j * block
        lower = (col < row).astype(jnp.float32)             # [TI, TJ]
        ids_j = ids_ref[0, :]                               # [TJ]
        seg = jax.lax.broadcasted_iota(jnp.int32,
                                       (block, n_dest), 1)  # [TJ, S]
        onehot = (seg == ids_j[:, None]).astype(jnp.float32)
        out_ref[:] += jnp.dot(lower, onehot,
                              preferred_element_type=jnp.float32)


def rank_by_dest(dest: jax.Array, n_dest: int, *, block: int = 256,
                 use_pallas: bool | None = None,
                 interpret: bool | None = None) -> jax.Array:
    """rank[i] = position of message i within its destination group.

    dest: [B] int32 in [0, n_dest) — map invalid lanes to a sink id in
    [0, n_dest) *before* calling. Returns [B] int32.
    """
    B = dest.shape[0]
    d = dest.astype(jnp.int32)
    if use_pallas is None:
        use_pallas = B >= 512
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not use_pallas:
        # small batches: the O(B^2) pairwise mask fits comfortably on-chip
        row = d[:, None] == d[None, :]
        lower = jnp.tril(jnp.ones((B, B), jnp.bool_), -1)
        return jnp.sum(row & lower, axis=1).astype(jnp.int32)
    block = min(block, B)
    Bp = -(-B // block) * block
    Sp = max(8, -(-n_dest // 8) * 8)
    dp = jnp.pad(d, (0, Bp - B), constant_values=Sp - 1) if Bp != B else d
    counts = pl.pallas_call(
        functools.partial(_prefix_kernel, block=block, n_dest=Sp),
        grid=(Bp // block, Bp // block),
        in_specs=[pl.BlockSpec((1, block), lambda i, j: (0, j))],
        out_specs=pl.BlockSpec((block, Sp), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Bp, Sp), jnp.float32),
        interpret=interpret,
    )(dp[None, :])
    rank = jnp.take_along_axis(counts[:B], d[:, None], axis=1)[:, 0]
    return rank.astype(jnp.int32)


def rank_dense_keys(keys: jax.Array) -> jax.Array:
    """rank[i] = position of element i within its key group — the same
    prefix count as :func:`rank_by_dest`, for LARGE key spaces.

    Regime split: the MXU prefix-count builds an O(B x S) table — ideal
    when S is the shard count (routing), ruinous when S is an actor space
    (fan-in append to 64k timelines). Here the rank comes from one stable
    argsort + a cumulative max (O(B log^2 B) sort beats an O(B*S) table
    once S >> log^2 B). keys: [B] int32 (any values). Returns [B] int32.
    """
    B = keys.shape[0]
    order = jnp.argsort(keys, stable=True)
    ks = keys[order]
    idx = jnp.arange(B, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), ks[1:] != ks[:-1]])
    group_start = jax.lax.cummax(jnp.where(is_start, idx, 0))
    rank_sorted = idx - group_start
    return jnp.zeros((B,), jnp.int32).at[order].set(rank_sorted)


def pack_by_dest(dest: jax.Array, valid: jax.Array, payload: dict,
                 n_dest: int, capacity: int, **rank_kw):
    """Sort-free outbox pack (drop-in for transport._pack_outbox semantics).

    Returns (out_payload dict [n_dest, capacity, ...], out_valid
    [n_dest, capacity], drops scalar). Overflow beyond ``capacity`` per
    destination is dropped and counted — the overload-shedding analog of
    ``ActivationData.CheckOverloaded`` (ActivationData.cs:616).
    """
    in_range = (dest >= 0) & (dest < n_dest)
    ok = valid & in_range
    d = jnp.where(ok, dest, n_dest).astype(jnp.int32)
    if dest.shape[0] > 32768 and not rank_kw:
        # the MXU prefix count is O(B^2); past ~32k lanes the sort-based
        # rank's O(B log^2 B) wins even on TPU
        rank = rank_dense_keys(d)
    else:
        rank = rank_by_dest(d, n_dest + 1, **rank_kw)
    keep = ok & (rank < capacity)
    drops = jnp.sum(ok & ~keep) + jnp.sum(valid & ~in_range)
    sink = n_dest * capacity
    flat = jnp.where(keep, d * capacity + jnp.minimum(rank, capacity - 1),
                     sink)

    def scatter(x):
        buf = jnp.zeros((n_dest * capacity + 1, *x.shape[1:]), x.dtype)
        return buf.at[flat].set(x)[:-1].reshape(
            n_dest, capacity, *x.shape[1:])

    out_payload = jax.tree_util.tree_map(scatter, payload)
    out_valid = jnp.zeros((n_dest * capacity + 1,), bool).at[flat].set(
        keep)[:-1].reshape(n_dest, capacity)
    return out_payload, out_valid, drops
