"""Device-resident directory: batched hash-probe lookup on the chip.

The reference's grain directory is a host hash map partitioned over silos
(GrainDirectoryPartition.cs:207,215) with LRU/adaptive caches in front
(LRUBasedGrainDirectoryCache.cs, AdaptiveGrainDirectoryCache.cs). On TPU
the cache tier moves onto the device: an open-addressing table (power-of-
two capacity, linear probing, multiplicative hashing) stored as two int32
arrays. Inserts/removes are host-side (activation create/destroy is the
cold path — Catalog.GetOrCreateActivation, Catalog.cs:443); lookups are a
batched device op on the hot path, so a tick can resolve thousands of
``key → slot`` routes without a host round-trip.

Lookup is P parallel gathers (probe depth is static), not a Pallas kernel
*by design*: XLA lowers a [B, P] gather from an HBM-resident table
optimally, and there is no fusion or blocking a hand-written kernel would
add — the Pallas wins live in the reduce/pack ops (segment_reduce, route).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["EMPTY", "build_directory_arrays", "device_lookup",
           "DeviceDirectory", "device_lookup64", "DeviceDirectory64",
           "split64"]

EMPTY = -1
_MULT = np.uint32(2654435761)  # Knuth multiplicative hash


def _hash_np(keys: np.ndarray, cap: int) -> np.ndarray:
    return ((keys.astype(np.uint32) * _MULT) >> np.uint32(1)) % np.uint32(cap)


def _hash_jnp(keys: jax.Array, cap: int) -> jax.Array:
    h = (keys.astype(jnp.uint32) * jnp.uint32(_MULT)) >> jnp.uint32(1)
    return (h % jnp.uint32(cap)).astype(jnp.int32)


def _check_key(key: int) -> int:
    """Keys live in the 31-bit uniform-hash domain (int32 table cells with
    EMPTY = -1). Callers hold the full GrainId; what they index by here is
    its uniform hash already reduced to 31 bits (dispatch.engine masks with
    & 0x7FFFFFFF). Reject anything wider instead of silently aliasing."""
    if not 0 <= key < 2**31:
        raise ValueError(
            f"directory keys must be 31-bit uniform hashes, got {key}; "
            f"reduce with `key & 0x7FFFFFFF` at the call site")
    return key


def build_directory_arrays(entries: dict[int, int], capacity: int,
                           max_probes: int = 16):
    """Host-build (tkeys, tvals) int32 arrays from key→value pairs.

    capacity must be a power of two and > len(entries) (keep load factor
    ≤ 0.5 so ``max_probes`` bounds hold).
    """
    if capacity & (capacity - 1):
        raise ValueError("capacity must be a power of two")
    if len(entries) * 2 > capacity:
        raise ValueError(
            f"load factor too high: {len(entries)} entries / {capacity}")
    tkeys = np.full(capacity, EMPTY, dtype=np.int32)
    tvals = np.zeros(capacity, dtype=np.int32)
    for k, v in entries.items():
        k31 = _check_key(k)
        h = int(_hash_np(np.asarray(k31), capacity))
        for p in range(max_probes):
            idx = (h + p) % capacity
            if tkeys[idx] == EMPTY or tkeys[idx] == k31:
                tkeys[idx] = k31
                tvals[idx] = v
                break
        else:
            raise RuntimeError(
                f"probe depth {max_probes} exhausted inserting {k}")
    return tkeys, tvals


def device_lookup(tkeys: jax.Array, tvals: jax.Array, keys: jax.Array,
                  max_probes: int = 16):
    """Batched lookup: keys [B] → (vals [B] int32, found [B] bool).

    jit/shard_map-safe; missing keys return (0, False). Traced keys are
    reduced to the 31-bit domain with ``& 0x7FFFFFFF`` (a jit-traced array
    cannot raise); hosts inserting via DeviceDirectory are validated.
    """
    cap = tkeys.shape[0]
    k31 = (keys & 0x7FFFFFFF).astype(jnp.int32)
    h = _hash_jnp(k31, cap)                                  # [B]
    probes = (h[:, None] + jnp.arange(max_probes, dtype=jnp.int32)) % cap
    tk = tkeys[probes]                                       # [B, P]
    match = tk == k31[:, None]
    # linear probing invariant: the first EMPTY terminates the chain
    before_empty = jnp.cumprod((tk != EMPTY).astype(jnp.int32),
                               axis=1).astype(bool)
    hit = match & before_empty
    found = jnp.any(hit, axis=1)
    first = jnp.argmax(hit, axis=1)
    vals = tvals[jnp.take_along_axis(probes, first[:, None], axis=1)[:, 0]]
    return jnp.where(found, vals, 0), found


def split64(keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Split uint64-domain keys into (lo31, hi31) int32 halves — the wire
    layout for 62-bit uniform hashes on a 32-bit device (x64 stays off)."""
    k = np.asarray(keys, dtype=np.int64)
    lo = (k & 0x7FFFFFFF).astype(np.int32)
    hi = ((k >> 31) & 0x7FFFFFFF).astype(np.int32)
    return lo, hi


def device_lookup64(tk_lo: jax.Array, tk_hi: jax.Array, tvals: jax.Array,
                    keys_lo: jax.Array, keys_hi: jax.Array,
                    max_probes: int = 16):
    """Batched lookup with FULL 62-bit key identity: (lo, hi) [B] int32
    halves → (vals [B] int32, found [B] bool). The 31-bit probe hash comes
    from the low half; a hit requires BOTH halves to match, so distinct
    uniform hashes can never alias onto another actor's slot (the
    correctness bar for routing, vs the 31-bit cache-tier lookup)."""
    cap = tk_lo.shape[0]
    lo = (keys_lo & 0x7FFFFFFF).astype(jnp.int32)
    hi = (keys_hi & 0x7FFFFFFF).astype(jnp.int32)
    h = _hash_jnp(lo, cap)
    probes = (h[:, None] + jnp.arange(max_probes, dtype=jnp.int32)) % cap
    plo = tk_lo[probes]                                      # [B, P]
    phi = tk_hi[probes]
    match = (plo == lo[:, None]) & (phi == hi[:, None])
    before_empty = jnp.cumprod((plo != EMPTY).astype(jnp.int32),
                               axis=1).astype(bool)
    hit = match & before_empty
    found = jnp.any(hit, axis=1)
    first = jnp.argmax(hit, axis=1)
    vals = tvals[jnp.take_along_axis(probes, first[:, None], axis=1)[:, 0]]
    return jnp.where(found, vals, 0), found


class DeviceDirectory64:
    """Host-mutated, device-queried directory over full 62-bit keys:
    (lo31, hi31) split cells, linear probing on the low half, backward-
    shift delete. The authoritative key→slot map for sparse vector-grain
    keys in the on-device routing path (route/apply_received sparse mode —
    the on-chip analog of AdaptiveGrainDirectoryCache.cs:178, promoted
    from cache to resolver because both key halves are checked)."""

    def __init__(self, capacity: int = 1024, max_probes: int = 16):
        if capacity & (capacity - 1):
            raise ValueError("capacity must be a power of two")
        self.capacity = capacity
        self.max_probes = max_probes
        self.tk_lo = np.full(capacity, EMPTY, dtype=np.int32)
        self.tk_hi = np.zeros(capacity, dtype=np.int32)
        self.tvals = np.zeros(capacity, dtype=np.int32)
        self.count = 0
        self._dev: tuple[jax.Array, jax.Array, jax.Array] | None = None

    @staticmethod
    def _split(key: int) -> tuple[int, int]:
        if key < 0:
            raise ValueError(f"directory keys must be non-negative: {key}")
        return key & 0x7FFFFFFF, (key >> 31) & 0x7FFFFFFF

    def _probe_host(self, lo: int, hi: int) -> int | None:
        h = int(_hash_np(np.asarray(lo), self.capacity))
        for p in range(self.max_probes):
            idx = (h + p) % self.capacity
            if self.tk_lo[idx] == EMPTY or (
                    self.tk_lo[idx] == lo and self.tk_hi[idx] == hi):
                return idx
        return None

    def insert(self, key: int, val: int) -> None:
        if (self.count + 1) * 2 > self.capacity:
            self._grow()
        lo, hi = self._split(key)
        idx = self._probe_host(lo, hi)
        if idx is None:
            self._grow()
            idx = self._probe_host(lo, hi)
            assert idx is not None
        if self.tk_lo[idx] == EMPTY:
            self.count += 1
        self.tk_lo[idx] = lo
        self.tk_hi[idx] = hi
        self.tvals[idx] = val
        self._dev = None

    def remove(self, key: int) -> bool:
        lo, hi = self._split(key)
        h = int(_hash_np(np.asarray(lo), self.capacity))
        idx = None
        for p in range(self.max_probes):
            i = (h + p) % self.capacity
            if self.tk_lo[i] == lo and self.tk_hi[i] == hi:
                idx = i
                break
            if self.tk_lo[i] == EMPTY:
                return False
        if idx is None:
            return False
        self.tk_lo[idx] = EMPTY
        self.count -= 1
        j = (idx + 1) % self.capacity
        moved: list[tuple[int, int, int]] = []
        while self.tk_lo[j] != EMPTY:
            moved.append((int(self.tk_lo[j]), int(self.tk_hi[j]),
                          int(self.tvals[j])))
            self.tk_lo[j] = EMPTY
            self.count -= 1
            j = (j + 1) % self.capacity
        for mlo, mhi, mv in moved:
            i2 = self._probe_host(mlo, mhi)
            assert i2 is not None
            if self.tk_lo[i2] == EMPTY:
                self.count += 1
            self.tk_lo[i2] = mlo
            self.tk_hi[i2] = mhi
            self.tvals[i2] = mv
        self._dev = None
        return True

    def _grow(self) -> None:
        old = [(int(lo) | (int(hi) << 31), int(v))
               for lo, hi, v in zip(self.tk_lo, self.tk_hi, self.tvals)
               if lo != EMPTY]
        self.capacity *= 2
        self.tk_lo = np.full(self.capacity, EMPTY, dtype=np.int32)
        self.tk_hi = np.zeros(self.capacity, dtype=np.int32)
        self.tvals = np.zeros(self.capacity, dtype=np.int32)
        self.count = 0
        self._dev = None
        for k, v in old:
            self.insert(k, v)

    def device_arrays(self) -> tuple[jax.Array, jax.Array, jax.Array]:
        if self._dev is None:
            self._dev = (jnp.asarray(self.tk_lo), jnp.asarray(self.tk_hi),
                         jnp.asarray(self.tvals))
        return self._dev

    def lookup_batch(self, keys_lo, keys_hi) -> tuple[jax.Array, jax.Array]:
        lo, hi, tv = self.device_arrays()
        return device_lookup64(lo, hi, tv, jnp.asarray(keys_lo),
                               jnp.asarray(keys_hi), self.max_probes)

    def lookup(self, key: int) -> int | None:
        lo, hi = self._split(key)
        idx = self._probe_host(lo, hi)
        if idx is None or self.tk_lo[idx] != lo:
            return None
        return int(self.tvals[idx])


class DeviceDirectory:
    """Host-mutated, device-queried key→slot directory (the on-chip
    directory-cache tier; see module docstring).

    Host writes go to numpy shadows; the device copy refreshes lazily on
    the next batched lookup (write-behind, like the adaptive cache
    maintainer's batched revalidation — AdaptiveDirectoryCacheMaintainer.cs).
    """

    def __init__(self, capacity: int = 1024, max_probes: int = 16):
        if capacity & (capacity - 1):
            raise ValueError("capacity must be a power of two")
        self.capacity = capacity
        self.max_probes = max_probes
        self.tkeys = np.full(capacity, EMPTY, dtype=np.int32)
        self.tvals = np.zeros(capacity, dtype=np.int32)
        self.count = 0
        self._dev: tuple[jax.Array, jax.Array] | None = None

    def _probe_host(self, k31: int) -> int | None:
        h = int(_hash_np(np.asarray(k31), self.capacity))
        for p in range(self.max_probes):
            idx = (h + p) % self.capacity
            tk = self.tkeys[idx]
            if tk == EMPTY or tk == k31:
                return idx
        return None

    def insert(self, key: int, val: int) -> None:
        if (self.count + 1) * 2 > self.capacity:
            self._grow()
        k31 = _check_key(key)
        idx = self._probe_host(k31)
        if idx is None:
            self._grow()
            idx = self._probe_host(k31)
            assert idx is not None
        if self.tkeys[idx] == EMPTY:
            self.count += 1
        self.tkeys[idx] = k31
        self.tvals[idx] = val
        self._dev = None

    def remove(self, key: int) -> bool:
        """Tombstone-free removal: re-insert the tail of the probe cluster
        (standard open-addressing backward-shift delete)."""
        k31 = _check_key(key)
        h = int(_hash_np(np.asarray(k31), self.capacity))
        idx = None
        for p in range(self.max_probes):
            i = (h + p) % self.capacity
            if self.tkeys[i] == k31:
                idx = i
                break
            if self.tkeys[i] == EMPTY:
                return False
        if idx is None:
            return False
        # backward-shift: rehash the contiguous cluster after idx
        self.tkeys[idx] = EMPTY
        self.count -= 1
        j = (idx + 1) % self.capacity
        moved: list[tuple[int, int]] = []
        while self.tkeys[j] != EMPTY:
            moved.append((int(self.tkeys[j]), int(self.tvals[j])))
            self.tkeys[j] = EMPTY
            self.count -= 1
            j = (j + 1) % self.capacity
        for k, v in moved:
            # re-insert without growth: these entries already fit at this
            # capacity, and _grow here would drop the not-yet-reinserted tail
            i2 = self._probe_host(k)
            assert i2 is not None
            if self.tkeys[i2] == EMPTY:
                self.count += 1
            self.tkeys[i2] = k
            self.tvals[i2] = v
        self._dev = None
        return True

    def _grow(self) -> None:
        entries = {int(k): int(v)
                   for k, v in zip(self.tkeys, self.tvals) if k != EMPTY}
        self.capacity *= 2
        self.tkeys, self.tvals = build_directory_arrays(
            entries, self.capacity, self.max_probes)
        self._dev = None

    def device_arrays(self) -> tuple[jax.Array, jax.Array]:
        if self._dev is None:
            self._dev = (jnp.asarray(self.tkeys), jnp.asarray(self.tvals))
        return self._dev

    def lookup_batch(self, keys) -> tuple[jax.Array, jax.Array]:
        tk, tv = self.device_arrays()
        return device_lookup(tk, tv, jnp.asarray(keys), self.max_probes)

    def lookup(self, key: int) -> int | None:
        k31 = _check_key(key)
        idx = self._probe_host(k31)
        if idx is None or self.tkeys[idx] != k31:
            return None
        return int(self.tvals[idx])
