"""Fan-in segment reduction as MXU matmuls.

The fan-in hot op: N messages carrying values land on S target actors
(Presence GameGrain aggregating player heartbeats — reference
/root/reference/Samples/Presence/Grains/GameGrain.cs; every stream-consumer
fan-in has the same shape). The obvious ``jax.ops.segment_sum`` lowers to
an XLA scatter-add, which TPUs execute (mostly) serially — it is the
classic TPU anti-pattern. Both implementations here instead ride the MXU:

``segment_sum_onehot``
    out[s] = sum_i (seg_ids[i] == s) * values[i]  ==  onehot(seg_ids).T @ values
    — one [S, B] x [B, D] matmul. XLA fuses the one-hot mask into the
    matmul operand, so the O(S*B) mask is never materialized in HBM.

``segment_sum_pallas``
    The same contraction, hand-blocked: grid over (segment tiles, message
    tiles), the mask block built in VMEM from a broadcasted iota and fed
    straight to the MXU via ``jnp.dot``. Accumulates across message tiles
    in the output block (grid is sequential on TPU), so HBM traffic is
    one read of values/ids + one write of out.

``segment_sum`` picks the Pallas path on TPU for well-tiled shapes, the
one-hot path for other TPU shapes, and a plain scatter-add on non-TPU
backends (where the one-hot operand is pure overhead — the scatter IS the
fast path there; Pallas runs in interpret mode only for tests).

Accumulation note: the MXU paths accumulate in float32, exact for integer
values only below 2^24 per segment; the CPU scatter path sums exactly in
the input dtype. Per-segment totals beyond 2^24 should accumulate across
calls in caller state (as the bench's GameGrain does), not per call.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

__all__ = ["segment_sum", "segment_sum_onehot", "segment_sum_pallas",
           "masked_reduce", "host_fold", "REDUCE_OPS"]


def _as_2d(values: jax.Array) -> tuple[jax.Array, bool]:
    if values.ndim == 1:
        return values[:, None], True
    if values.ndim == 2:
        return values, False
    raise ValueError(f"values must be [B] or [B, D], got {values.shape}")


def segment_sum_onehot(values: jax.Array, seg_ids: jax.Array,
                       num_segments: int) -> jax.Array:
    """MXU segment sum: ``onehot(seg_ids).T @ values``.

    values: [B] or [B, D]; seg_ids: [B] int (out-of-range ids contribute
    nothing). Returns [S] or [S, D] in values.dtype (accumulated in f32).
    """
    v, squeeze = _as_2d(values)
    ids = seg_ids.astype(jnp.int32)
    seg_range = jax.lax.broadcasted_iota(jnp.int32, (num_segments, 1), 0)
    mask = (seg_range == ids[None, :]).astype(jnp.float32)  # [S, B]
    out = jnp.dot(mask, v.astype(jnp.float32),
                  preferred_element_type=jnp.float32)
    out = out.astype(values.dtype)
    return out[:, 0] if squeeze else out


def _seg_kernel(ids_ref, v_ref, out_ref, *, block_s: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    i = pl.program_id(0)
    seg_base = i * block_s
    ids = ids_ref[0, :]                                  # [TB]
    seg = jax.lax.broadcasted_iota(jnp.int32, (block_s, ids.shape[0]), 0)
    mask = (seg + seg_base == ids[None, :]).astype(jnp.float32)  # [TS, TB]
    out_ref[:] += jnp.dot(mask, v_ref[:].astype(jnp.float32),
                          preferred_element_type=jnp.float32)


def segment_sum_pallas(values: jax.Array, seg_ids: jax.Array,
                       num_segments: int, *, block_s: int = 256,
                       block_b: int = 512,
                       interpret: bool | None = None) -> jax.Array:
    """Blocked-MXU segment sum (see module docstring). Pads B and S up to
    tile multiples; out-of-range ids never match a segment tile."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    v, squeeze = _as_2d(values)
    B, D = v.shape
    ids = seg_ids.astype(jnp.int32)
    block_s = min(block_s, max(8, num_segments))
    block_b = min(block_b, max(128, B))
    Bp = -(-B // block_b) * block_b
    Sp = -(-num_segments // block_s) * block_s
    if Bp != B:
        v = jnp.pad(v, ((0, Bp - B), (0, 0)))
        ids = jnp.pad(ids, (0, Bp - B), constant_values=-1)
    out = pl.pallas_call(
        functools.partial(_seg_kernel, block_s=block_s),
        grid=(Sp // block_s, Bp // block_b),
        in_specs=[
            pl.BlockSpec((1, block_b), lambda i, j: (0, j)),
            pl.BlockSpec((block_b, D), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((block_s, D), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Sp, D), jnp.float32),
        interpret=interpret,
    )(ids[None, :], v)
    out = out[:num_segments].astype(values.dtype)
    return out[:, 0] if squeeze else out


# ---------------------------------------------------------------------------
# Masked full reduction (the reduce_actors device half)
# ---------------------------------------------------------------------------

# combine ops reduce_actors accepts. "mean" is NOT here deliberately: it
# is not associative per-silo — callers combine it as (sum, count) pairs
# and divide once at the top (the engine and the dispatcher's cross-silo
# merge both do), so partial reductions stay exactly combinable.
REDUCE_OPS = ("sum", "max", "min")


def host_fold(op: str):
    """The numpy fold that combines :func:`masked_reduce` partials
    host-side (across deferral rounds and across silos) — the ONE place
    the op → fold mapping lives, so the engine's round combiner and the
    dispatcher's cross-silo merge cannot drift when an op is added.
    ``mean`` partials carry sums (divide once at the top)."""
    if op in ("sum", "mean"):
        return np.add
    if op == "max":
        return np.maximum
    if op == "min":
        return np.minimum
    raise ValueError(f"op must be one of {REDUCE_OPS + ('mean',)}, "
                     f"got {op!r}")


def _reduce_identity(op: str, dtype) -> jax.Array:
    """The op's identity element in ``dtype`` — what masked-off lanes
    contribute. Integer sums stay in the integer dtype (exact,
    order-independent: the determinism contract reduce_actors tests pin);
    float sums keep the value dtype and are bit-stable only per layout."""
    if op == "sum":
        return jnp.zeros((), dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        v = -jnp.inf if op == "max" else jnp.inf
        return jnp.asarray(v, dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(op == "min", jnp.bool_)
    info = np.iinfo(np.dtype(dtype))
    return jnp.asarray(info.min if op == "max" else info.max, dtype)


@functools.partial(jax.jit, static_argnames=("op",))
def masked_reduce(values, valid: jax.Array, op: str = "sum"):
    """Full tree reduction of per-lane results down to ONE row.

    values: pytree of ``[n_shards, B, *feature]`` arrays (a tick's
    per-actor results); valid: ``[n_shards, B]`` bool. Reduces every leaf
    over the two lane axes — masked lanes contribute the op's identity —
    returning a pytree of ``[*feature]`` arrays: the single row that
    crosses the host boundary instead of N per-actor responses
    (DrJAX-style MapReduce leaf, arXiv 2403.07128).

    Accumulation dtype is the value dtype: integer sums are exact and
    layout-independent (the reduce_actors determinism contract — bool
    promotes to int32, the readiness-count case); float sums reduce in a
    deterministic tree order per shape but differ across shard layouts
    by normal float reassociation. All-masked max/min returns the
    identity — callers hold the valid count and decide."""
    if op not in REDUCE_OPS:
        raise ValueError(f"op must be one of {REDUCE_OPS}, got {op!r}")

    def one(v):
        dtype = v.dtype
        if op == "sum" and dtype == jnp.bool_:
            v = v.astype(jnp.int32)   # bool sum = count of True lanes
            dtype = v.dtype
        mask = valid.reshape(valid.shape + (1,) * (v.ndim - valid.ndim))
        filled = jnp.where(mask, v, _reduce_identity(op, dtype))
        if op == "sum":
            return jnp.sum(filled, axis=(0, 1))
        if op == "max":
            return jnp.max(filled, axis=(0, 1))
        return jnp.min(filled, axis=(0, 1))

    return jax.tree_util.tree_map(one, values)


def segment_sum(values: jax.Array, seg_ids: jax.Array,
                num_segments: int) -> jax.Array:
    """Fan-in reduction, backend-dispatched: the Pallas MXU kernel on TPU
    when the shape tiles well, the fused one-hot matmul for other TPU
    shapes (scatter-add is the weak op there), and a plain scatter-add
    everywhere else — on CPU the one-hot path materializes an O(B×S)
    operand for no benefit (measured 2.3× slower at B=156k, S=128 in the
    multi-shard bench's fan-in)."""
    v2, _ = _as_2d(values)  # enforce the [B]/[B,D] contract on EVERY
    # backend, so shapes that would fail on TPU fail on CPU too
    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        return jax.ops.segment_sum(values, seg_ids,
                                   num_segments=num_segments)
    B, D = v2.shape
    if B >= 1024 and num_segments >= 256 and D % 128 == 0:
        return segment_sum_pallas(values, seg_ids, num_segments,
                                  interpret=False)
    return segment_sum_onehot(values, seg_ids, num_segments)
