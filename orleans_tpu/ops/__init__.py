"""TPU hot-op kernel library (Pallas + MXU-shaped XLA).

The reference implements its performance-critical machinery as raw sockets,
pinned buffer pools and IL-emitted serializers (SURVEY.md preamble;
/root/reference/src/Orleans.Core/Messaging/SocketManager.cs,
Serialization/ILSerializerGenerator.cs). The TPU build's native tier is
this module: the per-tick dispatch hot ops re-expressed for the MXU/VPU —
fan-in reduction as blocked one-hot matmuls, destination ranking as a
triangular matmul instead of a sort, and directory lookup as vectorized
hash probing — with Pallas kernels where blocking/fusion beats what XLA
emits.
"""

from .hash_probe import DeviceDirectory, build_directory_arrays, device_lookup
from .route import pack_by_dest, rank_by_dest, rank_dense_keys
from .segment_reduce import (
    host_fold,
    masked_reduce,
    segment_sum,
    segment_sum_onehot,
    segment_sum_pallas,
)

__all__ = [
    "host_fold",
    "masked_reduce",
    "segment_sum",
    "segment_sum_onehot",
    "segment_sum_pallas",
    "rank_by_dest",
    "rank_dense_keys",
    "pack_by_dest",
    "device_lookup",
    "build_directory_arrays",
    "DeviceDirectory",
]
