"""Placement strategies (reference L7)."""

from .strategies import (  # noqa: F401
    ActivationCountP2CPlacement,
    ActivationCountPlacement,
    PlacementDirector,
    PlacementManager,
)
