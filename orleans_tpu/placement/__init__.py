"""Placement strategies (reference L7)."""

from .strategies import PlacementDirector, PlacementManager  # noqa: F401
