"""Placement directors (reference L7).

Re-design of /root/reference/src/Orleans.Runtime/Placement/: directors
``RandomPlacementDirector.cs:8``, ``PreferLocalPlacementDirector.cs:13``,
``HashBasedPlacementDirector.cs:6``, ``ActivationCountPlacementDirector.cs:13``
(+ ``DeploymentLoadPublisher.cs:17`` stats), ``StatelessWorkerDirector.cs:8``
(handled in-catalog as local replicas), managed by
``PlacementDirectorsManager.cs:9``.

Directors run on the directory-owner silo at first-placement time (the
``AddressMessage`` path): given the requesting silo and the current cluster
view, choose the silo that will host the new activation.
"""

from __future__ import annotations

import random
from typing import Callable, Protocol

from ..core.ids import GrainId, SiloAddress

__all__ = ["PlacementDirector", "PlacementManager",
           "ActivationCountPlacement", "ActivationCountP2CPlacement"]


class PlacementDirector(Protocol):
    def place(self, grain_id: GrainId, requester: SiloAddress,
              silos: list[SiloAddress]) -> SiloAddress: ...


class RandomPlacement:
    """Default strategy (RandomPlacementDirector.cs:8)."""

    def place(self, grain_id, requester, silos):
        return random.choice(silos)


class PreferLocalPlacement:
    """Requesting silo if alive, else random (PreferLocalPlacementDirector)."""

    def place(self, grain_id, requester, silos):
        if requester in silos:
            return requester
        return random.choice(silos)


class HashBasedPlacement:
    """Deterministic by grain hash (HashBasedPlacementDirector.cs:6)."""

    def place(self, grain_id, requester, silos):
        ordered = sorted(silos, key=lambda s: s.uniform_hash)
        return ordered[grain_id.uniform_hash % len(ordered)]


class ActivationCountPlacement:
    """Least-loaded by activation count (ActivationCountPlacementDirector
    + DeploymentLoadPublisher stats). ``load_of`` abstracts the stats feed;
    in-proc fabrics read counts directly, multi-host deployments plug the
    publisher's view in.

    Full scan (the default): every candidate's load is read and the
    minimum wins — the strongest balance, at O(silos) stat reads per
    placement. For large clusters under churn use the power-of-two-choices
    variant (``activation_count_p2c``)."""

    def __init__(self, load_of: Callable[[SiloAddress], int]):
        self.load_of = load_of

    def place(self, grain_id, requester, silos):
        return min(silos, key=self.load_of)


class ActivationCountP2CPlacement(ActivationCountPlacement):
    """Power-of-two-choices variant: sample TWO random silos (plus the
    requester) and take the least loaded — Orleans's own
    ActivationCountPlacementDirector samples rather than scanning, because
    with k=2 random choices the max load is within O(log log n) of optimal
    while stat reads stay O(1) per placement regardless of cluster size."""

    def place(self, grain_id, requester, silos):
        candidates = random.sample(silos, min(2, len(silos)))
        if requester in silos:
            candidates.append(requester)
        return min(candidates, key=self.load_of)


class PlacementManager:
    """Strategy-name → director registry (PlacementDirectorsManager.cs:9)."""

    def __init__(self, load_of: Callable[[SiloAddress], int] | None = None):
        load_of = load_of or (lambda s: 0)
        self.directors: dict[str, PlacementDirector] = {
            "random": RandomPlacement(),
            "prefer_local": PreferLocalPlacement(),
            "hash": HashBasedPlacement(),
            "activation_count": ActivationCountPlacement(load_of),
            "activation_count_p2c": ActivationCountP2CPlacement(load_of),
        }

    def director_by_name(self, name: str | None) -> PlacementDirector:
        if name == "stateless_worker":
            # stateless workers replicate locally; the caller's silo hosts
            return self.directors["prefer_local"]
        return self.directors.get(name or "random", self.directors["random"])

    def director_for(self, grain_class: type | None) -> PlacementDirector:
        name = getattr(grain_class, "__orleans_placement__", None) \
            if grain_class is not None else None
        return self.director_by_name(name)
