"""Interface versioning (reference src/Orleans.Runtime/Versions/)."""

from .manager import (
    TypeManagerTarget,
    VersionManager,
    grain_version,
    version_of,
)

__all__ = ["grain_version", "version_of", "VersionManager",
           "TypeManagerTarget"]
