"""Interface versioning: compat directors + selectors gating placement.

Re-design of /root/reference/src/Orleans.Runtime/Versions/: per-interface
version (codegen [Version(n)] attribute → ``@grain_version(n)`` here),
compatibility directors (``Compatibility/BackwardCompatilityDirector.cs``,
``StrictVersionCompatibilityDirector.cs``, ``AllVersionsCompatibilityDirector.cs``)
and selectors (``Selector/MinimumVersionSelector.cs``, ``LatestVersionSelector``,
``AllCompatibleVersions``), enforced where the reference enforces at
addressing time (``Dispatcher.cs:725-732``): the directory owner filters
placement candidates to silos hosting a compatible version
(``CachedVersionSelectorManager.cs``).

The cluster version map is exchanged the way the reference's TypeManager
does it (``GrainTypeManager/TypeManager.cs:15`` — a per-silo system target
plus a refresh timer): every silo serves its local interface→version map
from :class:`TypeManagerTarget`, and :class:`VersionManager` pulls peers'
maps on a refresh loop + on membership change. In-proc fabrics can still
read peer registries directly as a freshness shortcut, but gating no
longer silently passes when no info is reachable — an unknown silo simply
is not a placement candidate until its map arrives.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import TYPE_CHECKING, Callable

from ..core.ids import SiloAddress

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.versions")

__all__ = ["grain_version", "version_of", "VersionManager",
           "TypeManagerTarget", "TYPE_MANAGER_TARGET"]

TYPE_MANAGER_TARGET = "type-manager"
MAP_REFRESH_PERIOD = 2.0


def grain_version(version: int) -> Callable[[type], type]:
    """Class decorator declaring the grain interface version ([Version(n)])."""

    def deco(cls: type) -> type:
        cls.__orleans_version__ = version
        return cls

    return deco


def version_of(cls: type | None) -> int:
    return getattr(cls, "__orleans_version__", 0) if cls else 0


# -- compatibility directors -------------------------------------------------

def backward_compatible(requested: int, available: int) -> bool:
    """BackwardCompatilityDirector: a silo can serve any request compiled
    against its version or older."""
    return available >= requested


def strict_compatible(requested: int, available: int) -> bool:
    """StrictVersionCompatibilityDirector: exact match only."""
    return available == requested


def all_compatible(requested: int, available: int) -> bool:
    """AllVersionsCompatibilityDirector: anything goes."""
    return True


_COMPAT = {
    "backward": backward_compatible,
    "strict": strict_compatible,
    "all": all_compatible,
}

_SELECTORS = ("all_compatible", "latest_version", "minimum_version")


class TypeManagerTarget:
    """Per-silo system target serving the local interface→version map
    (the TypeManager system target, TypeManager.cs:15)."""

    def __init__(self, manager: "VersionManager"):
        self.manager = manager

    async def type_map(self) -> dict[str, int]:
        return self.manager.local_map()


class VersionManager:
    """Per-silo versioning policy: filter placement candidates for an
    interface+requested-version pair, against exchanged type maps."""

    def __init__(self, silo: "Silo", compat: str = "backward",
                 selector: str = "all_compatible"):
        if compat not in _COMPAT:
            raise ValueError(f"unknown compatibility strategy {compat!r}")
        if selector not in _SELECTORS:
            raise ValueError(f"unknown version selector {selector!r}")
        self.silo = silo
        self.compat = compat
        self.selector = selector
        # exchanged cluster type map: silo → {interface: version}
        self.remote_maps: dict[SiloAddress, dict[str, int]] = {}
        self._refresh_task: asyncio.Task | None = None
        self._fetch_tasks: set[asyncio.Task] = set()
        self.target = TypeManagerTarget(self)

    # -- exchange (TypeManager refresh timer) ----------------------------
    def local_map(self) -> dict[str, int]:
        out = {cls.__name__: version_of(cls)
               for cls in self.silo.registry.all_classes()}
        for name, cls in getattr(self.silo, "vector_interfaces", {}).items():
            out.setdefault(name, version_of(cls))
        return out

    def start_exchange(self) -> None:
        if self._refresh_task is None:
            self._refresh_task = asyncio.get_running_loop().create_task(
                self._refresh_loop())

    def stop_exchange(self) -> None:
        if self._refresh_task is not None:
            self._refresh_task.cancel()
            self._refresh_task = None
        for t in list(self._fetch_tasks):
            t.cancel()

    def forget(self, silo: SiloAddress) -> None:
        self.remote_maps.pop(silo, None)

    def schedule_fetch(self, silo: SiloAddress) -> None:
        """Fetch one peer's map now (membership-change hook)."""
        if silo == self.silo.silo_address:
            return
        t = asyncio.ensure_future(self._fetch(silo))
        self._fetch_tasks.add(t)
        t.add_done_callback(self._fetch_tasks.discard)

    async def _refresh_loop(self) -> None:
        while True:
            await asyncio.sleep(MAP_REFRESH_PERIOD)
            try:
                peers = [s for s in self.silo.locator.alive_list
                         if s != self.silo.silo_address]
                for peer in peers:
                    await self._fetch(peer)
                for known in list(self.remote_maps):
                    if known not in peers:
                        self.remote_maps.pop(known, None)
            except Exception:  # noqa: BLE001
                log.debug("type-map refresh round failed", exc_info=True)

    async def _fetch(self, peer: SiloAddress) -> None:
        from ..core.ids import GrainId, type_code_of
        from ..core.message import Category
        target = GrainId.system_target(
            type_code_of(TYPE_MANAGER_TARGET), peer)
        try:
            m = await self.silo.runtime_client.send_request(
                target_grain=target, grain_class=TypeManagerTarget,
                interface_name="TypeManagerTarget", method_name="type_map",
                args=(), kwargs={}, target_silo=peer,
                category=Category.SYSTEM, timeout=5.0)
            self.remote_maps[peer] = dict(m)
        except Exception:  # noqa: BLE001 — peer mid-death/mid-start; the
            # refresh loop re-tries, and unknown silos aren't candidates
            log.debug("type-map fetch from %s failed", peer)

    def set_strategy(self, compat: str | None = None,
                     selector: str | None = None) -> None:
        """Runtime strategy update (ManagementGrain.SetCompatibilityStrategy)."""
        if compat is not None:
            if compat not in _COMPAT:
                raise ValueError(f"unknown compatibility strategy {compat!r}")
            self.compat = compat
        if selector is not None:
            if selector not in _SELECTORS:
                raise ValueError(f"unknown version selector {selector!r}")
            self.selector = selector

    def available_version(self, silo: SiloAddress,
                          interface_name: str) -> int | None:
        """Version of ``interface_name`` hosted by ``silo`` (None = class not
        registered there, or the silo's type map has not arrived yet —
        either way it is not a candidate)."""
        if silo == self.silo.silo_address:
            cls = self.silo.registry.resolve(interface_name)
            if cls is None:
                cls = self.silo.vector_interfaces.get(interface_name)
            return None if cls is None else version_of(cls)
        # in-proc fabric shortcut: the peer's live registry IS the map
        peer = getattr(self.silo.fabric, "silos", {}).get(silo)
        if peer is not None:
            cls = peer.registry.resolve(interface_name)
            if cls is None:
                cls = peer.vector_interfaces.get(interface_name)
            return None if cls is None else version_of(cls)
        # cross-process: the exchanged map (TypeManager)
        m = self.remote_maps.get(silo)
        return None if m is None else m.get(interface_name)

    def compatible_silos(self, interface_name: str, requested: int,
                         candidates: list[SiloAddress]) -> list[SiloAddress]:
        ok = _COMPAT[self.compat]
        versions = {}
        for s in candidates:
            v = self.available_version(s, interface_name)
            if v is not None and ok(requested, v):
                versions[s] = v
        if not versions:
            return []
        if self.selector == "latest_version":
            pick = max(versions.values())
        elif self.selector == "minimum_version":
            pick = min(versions.values())
        else:
            return list(versions)
        return [s for s, v in versions.items() if v == pick]
