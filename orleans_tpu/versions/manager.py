"""Interface versioning: compat directors + selectors gating placement.

Re-design of /root/reference/src/Orleans.Runtime/Versions/: per-interface
version (codegen [Version(n)] attribute → ``@grain_version(n)`` here),
compatibility directors (``Compatibility/BackwardCompatilityDirector.cs``,
``StrictVersionCompatibilityDirector.cs``, ``AllVersionsCompatibilityDirector.cs``)
and selectors (``Selector/MinimumVersionSelector.cs``, ``LatestVersionSelector``,
``AllCompatibleVersions``), enforced where the reference enforces at
addressing time (``Dispatcher.cs:725-732``): the directory owner filters
placement candidates to silos hosting a compatible version
(``CachedVersionSelectorManager.cs``).

The cluster version map: in-proc fabrics read peer registries directly (the
same shortcut the load publisher uses); cross-host deployments would ride
the TypeManager exchange.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable

from ..core.ids import SiloAddress

if TYPE_CHECKING:
    from ..runtime.silo import Silo

__all__ = ["grain_version", "version_of", "VersionManager"]


def grain_version(version: int) -> Callable[[type], type]:
    """Class decorator declaring the grain interface version ([Version(n)])."""

    def deco(cls: type) -> type:
        cls.__orleans_version__ = version
        return cls

    return deco


def version_of(cls: type | None) -> int:
    return getattr(cls, "__orleans_version__", 0) if cls else 0


# -- compatibility directors -------------------------------------------------

def backward_compatible(requested: int, available: int) -> bool:
    """BackwardCompatilityDirector: a silo can serve any request compiled
    against its version or older."""
    return available >= requested


def strict_compatible(requested: int, available: int) -> bool:
    """StrictVersionCompatibilityDirector: exact match only."""
    return available == requested


def all_compatible(requested: int, available: int) -> bool:
    """AllVersionsCompatibilityDirector: anything goes."""
    return True


_COMPAT = {
    "backward": backward_compatible,
    "strict": strict_compatible,
    "all": all_compatible,
}

_SELECTORS = ("all_compatible", "latest_version", "minimum_version")


class VersionManager:
    """Per-silo versioning policy: filter placement candidates for an
    interface+requested-version pair."""

    def __init__(self, silo: "Silo", compat: str = "backward",
                 selector: str = "all_compatible"):
        if compat not in _COMPAT:
            raise ValueError(f"unknown compatibility strategy {compat!r}")
        if selector not in _SELECTORS:
            raise ValueError(f"unknown version selector {selector!r}")
        self.silo = silo
        self.compat = compat
        self.selector = selector

    def set_strategy(self, compat: str | None = None,
                     selector: str | None = None) -> None:
        """Runtime strategy update (ManagementGrain.SetCompatibilityStrategy)."""
        if compat is not None:
            if compat not in _COMPAT:
                raise ValueError(f"unknown compatibility strategy {compat!r}")
            self.compat = compat
        if selector is not None:
            if selector not in _SELECTORS:
                raise ValueError(f"unknown version selector {selector!r}")
            self.selector = selector

    def available_version(self, silo: SiloAddress,
                          interface_name: str) -> int | None:
        """Version of ``interface_name`` hosted by ``silo`` (None = class not
        registered there)."""
        peer = self.silo.fabric.silos.get(silo)
        if peer is None:
            return None
        cls = peer.registry.resolve(interface_name)
        return None if cls is None else version_of(cls)

    def compatible_silos(self, interface_name: str, requested: int,
                         candidates: list[SiloAddress]) -> list[SiloAddress]:
        ok = _COMPAT[self.compat]
        versions = {}
        for s in candidates:
            v = self.available_version(s, interface_name)
            if v is not None and ok(requested, v):
                versions[s] = v
        if not versions:
            return []
        if self.selector == "latest_version":
            pick = max(versions.values())
        elif self.selector == "minimum_version":
            pick = min(versions.values())
        else:
            return list(versions)
        return [s for s, v in versions.items() if v == pick]
