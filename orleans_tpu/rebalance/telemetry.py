"""Hot-spot telemetry: fold both tiers' load signals into one report.

The device half lives where the heat is generated: every dispatch tick
scatter-adds its batch into the table's per-slot hit counters ON DEVICE
(``ShardedActorTable.record_hits`` — no host sync on the hot path), and
this module only reads them out at planner rate. The host half is the
catalog/mailbox view the reference's ``DeploymentLoadPublisher`` publishes
(DeploymentLoadPublisher.cs:85); the publisher folds :func:`load_report`
into every broadcast so peers' planners see queue depth and device-shard
heat, not just activation counts.
"""

from __future__ import annotations

import numpy as np

__all__ = ["load_report", "vector_shard_hits", "queue_depth",
           "hot_hashed_keys"]


def vector_shard_hits(silo) -> dict[str, list[int]]:
    """Per-class per-shard invocation totals since the last planner reset
    (empty until ``enable_load_tracking``)."""
    rt = getattr(silo, "vector", None)
    if rt is None or not rt.track_load:
        return {}
    return {cls.__name__: [int(x) for x in hits]
            for cls, hits in rt.shard_loads().items()}


def queue_depth(silo) -> int:
    """Backlogged work on this silo: application inbound queue + parked
    activation mailboxes + device-tier pending (incl. conflict-deferred)
    — the queue-depth load signal next to the activation count."""
    from ..core.message import Category

    depth = 0
    q = silo.message_center.inbound.get(Category.APPLICATION)
    if q is not None:
        depth += q.qsize()
    depth += sum(len(a.waiting) + len(a.activating_backlog)
                 for a in silo.catalog.by_activation.values())
    rt = getattr(silo, "vector", None)
    if rt is not None:
        depth += rt.queue_depth()
    return depth


def load_report(silo) -> dict:
    """The extended per-silo load report (what the publisher broadcasts)."""
    return {
        "activation_count": silo.catalog.activation_count(),
        "queue_depth": queue_depth(silo),
        "vector_hits": vector_shard_hits(silo),
    }


def hot_hashed_keys(tbl, shard: int, limit: int,
                    slot_hits: np.ndarray | None = None) -> np.ndarray:
    """Hashed-regime keys resident on ``shard``, hottest first, at most
    ``limit`` — the victim pool for a device-tier shard drain. Dense-regime
    rows never appear (their re-range is the explicit ``reshard_dense``
    snapshot path). Pass ``slot_hits`` (a prior ``tbl.slot_hits()``) to
    avoid a second full device→host counter transfer per round."""
    resident = [(kh, slot) for kh, (sh, slot) in tbl.key_to_slot.items()
                if sh == shard]
    if not resident:
        return np.zeros(0, dtype=np.int64)
    hits = (tbl.slot_hits() if slot_hits is None else slot_hits)[shard]
    resident.sort(key=lambda ks: int(hits[ks[1]]), reverse=True)
    return np.asarray([kh for kh, _ in resident[:limit]], dtype=np.int64)
