"""Live activation migration & load-aware rebalancing.

The runtime re-distribution tier: hot-spot telemetry accumulated on device
inside the dispatch tick (``dispatch.table``/``dispatch.engine``) and
folded into the silo load broadcast (``management.load_publisher``), a
planner that turns the cluster load view into a budget-bounded batched
migration plan (``ops.route.pack_by_dest`` packing + ``placement``
directors for destination choice), and a live executor — fence →
dehydrate → transfer → rehydrate → directory re-registration with cache
invalidation → mailbox re-dispatch, with rollback on failure.

Reference trajectory: DeploymentLoadPublisher +
ActivationCountPlacementDirector, later Orleans's activation
repartitioning; device half per "Memory-efficient array redistribution
through portable collective communication" (PAPERS.md).
"""

from .executor import REBALANCE_TARGET, MigrationExecutor  # noqa: F401
from .planner import (  # noqa: F401
    ActivationMove,
    MigrationPlan,
    RebalancePlanner,
    ShardMoves,
)
from .service import RebalanceTarget, Rebalancer, add_rebalancer  # noqa: F401
from .telemetry import load_report, queue_depth, vector_shard_hits  # noqa: F401

__all__ = [
    "Rebalancer", "RebalanceTarget", "add_rebalancer", "REBALANCE_TARGET",
    "MigrationExecutor", "RebalancePlanner", "MigrationPlan",
    "ActivationMove", "ShardMoves", "load_report", "queue_depth",
    "vector_shard_hits",
]
