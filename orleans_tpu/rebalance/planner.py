"""Rebalance planner: cluster load view → batched migration plan.

Each silo plans ONLY for itself as a source (the decentralized shape of
Orleans's activation repartitioning: every silo drains its own excess, no
global coordinator), across both tiers:

* **host tier** — when this silo's activation count exceeds the cluster
  mean by the configured hysteresis ratio, pick migration victims and a
  destination per victim through ``placement.strategies`` directors
  (``ActivationCountPlacement`` full scan, fed the planned loads so one
  round doesn't dogpile a single receiver).
* **device tier** — when one mesh shard's on-device hit counters run hot,
  drain its hottest hashed-regime rows toward cool shards. The candidate →
  destination assignment is packed with ``ops.route.pack_by_dest`` (the
  same MXU prefix-count pack the tick exchange uses): per-destination
  buckets, capacity = the round budget, overflow dropped and counted —
  budget enforcement IS the pack's overflow semantics.

This is the redistribution-planning half of "Memory-efficient array
redistribution through portable collective communication" (PAPERS.md)
applied to an actor table: plan on the host at planner rate, execute as
batched device copies.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from ..placement.strategies import ActivationCountPlacement
from .telemetry import hot_hashed_keys

__all__ = ["ActivationMove", "ShardMoves", "MigrationPlan",
           "RebalancePlanner"]


@dataclass
class ActivationMove:
    """One host-tier move: a local activation → a peer silo."""

    act: object           # runtime.activation.ActivationData
    dest: object          # SiloAddress


@dataclass
class ShardMoves:
    """Device-tier moves for one VectorGrain class (already packed and
    budget-bounded)."""

    cls: type
    keys: np.ndarray          # [M] int64 hashed key hashes
    dest_shards: np.ndarray   # [M] int32
    dropped: int = 0          # candidates beyond the per-round budget


@dataclass
class MigrationPlan:
    activation_moves: list[ActivationMove] = field(default_factory=list)
    shard_moves: list[ShardMoves] = field(default_factory=list)
    imbalance: float = 0.0    # observed hot/mean load ratio this round

    def __bool__(self) -> bool:
        return bool(self.activation_moves or self.shard_moves)

    @property
    def total(self) -> int:
        return len(self.activation_moves) + sum(
            len(m.keys) for m in self.shard_moves)


class RebalancePlanner:
    def __init__(self, silo, budget: int | None = None,
                 imbalance_ratio: float | None = None):
        self.silo = silo
        self.budget = budget if budget is not None \
            else silo.config.rebalance_budget
        self.imbalance_ratio = imbalance_ratio if imbalance_ratio is not None \
            else silo.config.rebalance_imbalance_ratio

    # ------------------------------------------------------------------
    def plan(self) -> MigrationPlan:
        plan = MigrationPlan()
        self._plan_activation_moves(plan)
        self._plan_ledger_moves(plan)
        self._plan_shard_moves(plan)
        return plan

    # -- host tier -------------------------------------------------------
    def _peer_loads(self) -> tuple[dict, dict]:
        """(activation counts, queue depths) per alive peer: the
        publisher's broadcast view when fresh, the in-proc fabric catalog
        read as fallback (the same two-source discipline as
        DistributedLocator._load_of). Queue depth is the secondary signal:
        the hysteresis and move count stay in activation-count units, but
        a backlogged peer is a worse destination than its count implies."""
        me = self.silo.silo_address
        publisher = getattr(self.silo, "load_publisher", None)
        loads, depths = {}, {}
        for s in self.silo.locator.alive_list:
            if s == me:
                continue
            report = publisher.report_of(s) if publisher is not None else None
            if report is not None:
                loads[s] = report["activation_count"]
                depths[s] = report.get("queue_depth", 0)
                continue
            peer = getattr(self.silo.fabric, "silos", {}).get(s)
            if peer is not None and peer.status == "Running":
                from .telemetry import queue_depth
                loads[s] = peer.catalog.activation_count()
                depths[s] = queue_depth(peer)
        return loads, depths

    def _victims(self, n: int) -> list:
        """Local activations cheapest to move, idle-first: VALID
        application grains with no timers (timer continuity across a move
        is a follow-on — a fence would silently kill them today) and no
        in-flight activation work."""
        from ..runtime.activation import ActivationState

        out = []
        for act in self.silo.catalog.by_activation.values():
            if act.grain_id.is_system_target():
                continue
            if act.state != ActivationState.VALID:
                continue
            if act.is_stateless_worker or act.timers:
                continue
            if act.activating_backlog:
                continue
            out.append(act)
        # idle activations first (nothing to drain), longest-idle first
        out.sort(key=lambda a: (not a.is_inactive, -a.idle_for()))
        return out[:n]

    def _plan_activation_moves(self, plan: MigrationPlan) -> None:
        peers, depths = self._peer_loads()
        if not peers:
            return
        my_load = self.silo.catalog.activation_count()
        mean = (my_load + sum(peers.values())) / (len(peers) + 1)
        if mean > 0:
            plan.imbalance = max(plan.imbalance, my_load / mean)
        if my_load <= self.imbalance_ratio * mean or \
                my_load - min(peers.values()) < 2:
            return
        n = min(self.budget, my_load - math.ceil(mean))
        if n <= 0:
            return
        # destination per victim through the placement director, fed the
        # PLANNED loads (each assignment bumps its target) so one round's
        # moves spread instead of dogpiling the single coldest peer; a
        # peer's queue depth rides along as a penalty so a count-cold but
        # backlogged silo is not the automatic winner
        planned = dict(peers)
        director = ActivationCountPlacement(
            lambda s: planned.get(s, 1 << 30) + depths.get(s, 0))
        candidates = list(planned)
        for act in self._victims(n):
            dest = director.place(act.grain_id, self.silo.silo_address,
                                  candidates)
            if planned[dest] + 1 >= my_load - len(plan.activation_moves):
                break  # moving further would just invert the imbalance
            planned[dest] += 1
            plan.activation_moves.append(ActivationMove(act, dest))

    def _plan_ledger_moves(self, plan: MigrationPlan) -> None:
        """Host-tier HOT-ACTOR moves from the cost ledger
        (``RebalanceOptions.use_ledger``): activation counts say WHERE
        activations live, the ledger says WHO is burning — a silo whose
        counts look balanced can still host the cluster's hottest keys,
        and the count-based pass above will never move them. Keys whose
        charged seconds exceed the imbalance ratio × the tracked mean
        become migration candidates toward the coolest peers, sharing
        the round's move budget with (and deduped against) the
        count-based pass. The label scheme is EXACTLY the dispatcher's
        charge key ("Class/key"), so resolution back to a local
        activation is a dict lookup, not a scan per label."""
        if not getattr(self.silo.config, "rebalance_use_ledger", False):
            return
        led = getattr(self.silo, "ledger", None)
        if led is None or not led.keys.counts:
            return
        budget = self.budget - len(plan.activation_moves)
        if budget <= 0:
            return
        peers, depths = self._peer_loads()
        if not peers:
            return
        ranked = led.keys.top()
        mean = sum(r[1] for r in ranked) / len(ranked)
        if mean <= 0:
            return
        hot_labels = [label for label, seconds, _err in ranked
                      if seconds > self.imbalance_ratio * mean]
        if not hot_labels:
            return
        from ..runtime.activation import ActivationState

        already = {id(m.act) for m in plan.activation_moves}
        by_label: dict[str, object] = {}
        for act in self.silo.catalog.by_activation.values():
            gid = act.grain_id
            if gid.is_system_target() or \
                    act.state != ActivationState.VALID:
                continue
            if act.is_stateless_worker or act.timers or \
                    act.activating_backlog or id(act) in already:
                continue
            by_label[f"{act.grain_class.__name__}/{gid.key}"] = act
        planned = dict(peers)
        for m in plan.activation_moves:
            planned[m.dest] = planned.get(m.dest, 0) + 1
        director = ActivationCountPlacement(
            lambda s: planned.get(s, 1 << 30) + depths.get(s, 0))
        candidates = list(planned)
        for label in hot_labels:
            if budget <= 0:
                break
            act = by_label.get(label)
            if act is None:
                continue  # remote, device-tier, or not movable here
            dest = director.place(act.grain_id, self.silo.silo_address,
                                  candidates)
            planned[dest] += 1
            plan.activation_moves.append(ActivationMove(act, dest))
            budget -= 1

    # -- device tier -----------------------------------------------------
    def _plan_shard_moves(self, plan: MigrationPlan) -> None:
        rt = getattr(self.silo, "vector", None)
        if rt is None or not rt.track_load:
            return
        for cls, tbl in rt.tables.items():
            if tbl.n_shards < 2 or not tbl.key_to_slot:
                continue
            hits = tbl.shard_hits().astype(np.float64)
            total = float(hits.sum())
            if total <= 0:
                continue
            mean = total / tbl.n_shards
            hot = int(np.argmax(hits))
            plan.imbalance = max(plan.imbalance, float(hits[hot]) / mean)
            if hits[hot] <= self.imbalance_ratio * mean:
                continue
            slot_hits = tbl.slot_hits()  # ONE counter readout per round
            keys = hot_hashed_keys(tbl, hot, self.budget,
                                   slot_hits=slot_hits)
            if not len(keys):
                continue
            moves = self._pack_shard_moves(tbl, hot, hits, keys,
                                           slot_hits[hot])
            if moves is not None:
                moves = ShardMoves(cls, *moves)
                if len(moves.keys):
                    plan.shard_moves.append(moves)

    def _pack_shard_moves(self, tbl, hot: int, hits: np.ndarray,
                          keys: np.ndarray, slot_hits: np.ndarray):
        """Assign each candidate a cool destination shard (greedy: always
        the currently-coolest, updating as the key's own heat lands), then
        pack the assignment with ``pack_by_dest``. ``slot_hits``: the hot
        shard's row of the round's single counter readout."""
        from ..ops.route import pack_by_dest

        planned = hits.copy()
        dests = np.empty(len(keys), dtype=np.int32)
        n_assigned = 0
        for kh in keys:
            dest = int(np.argmin(planned))
            if dest == hot:
                break  # hot shard became coolest: balance reached
            dests[n_assigned] = dest
            heat = float(slot_hits[tbl.key_to_slot[int(kh)][1]])
            planned[dest] += heat
            planned[hot] -= heat
            n_assigned += 1
        if n_assigned == 0:
            return None
        keys, dests = keys[:n_assigned], dests[:n_assigned]
        # pack candidate INDICES, not the keys: 63-bit key hashes do not
        # survive an int32 payload (bit 62 is set for half of all string
        # keys), and indices are what the pack actually needs — the keys
        # are recovered host-side from the candidate array
        payload = {"idx": jnp.arange(len(keys), dtype=jnp.int32)}
        valid = jnp.ones(len(keys), dtype=bool)
        out, out_valid, drops = pack_by_dest(
            jnp.asarray(dests), valid, payload, tbl.n_shards, self.budget)
        idx = np.asarray(out["idx"])
        ok = np.asarray(out_valid)
        dest_grid = np.broadcast_to(
            np.arange(tbl.n_shards, dtype=np.int32)[:, None], ok.shape)
        return keys[idx[ok]], dest_grid[ok].astype(np.int32), int(drops)
