"""Migration executor: apply a MigrationPlan live, with fences + rollback.

Host tier — one activation at a time, the dehydrate/transfer/rehydrate
protocol of Orleans grain migration (the activation-repartitioning
trajectory the reference grew after DeploymentLoadPublisher):

1. **fence** — flip the activation to DEACTIVATING so the dispatcher parks
   every arriving message in its mailbox (no turn may observe state that
   is mid-copy), then drain running turns (bounded).
2. **transfer** — ship (grain id, class, in-memory state, activation id)
   to the destination's RebalanceTarget over the silo fabric.
3. **rehydrate + re-register** — the destination builds the activation,
   arms the storage etag, overlays the migrated state, and REPLACES the
   directory registration through ``locator.migrate_register`` (with
   cache invalidation; stale peer caches heal via invalidation-on-forward).
4. **commit** — only after the destination acks does the source destroy
   its copy and re-dispatch the parked mailbox (the messages that raced
   the move re-address against the updated directory — zero lost, zero
   duplicated: none of them ever started a turn here).
5. **rollback** — any transfer failure re-registers the source (it never
   unregistered; ``register`` is first-wins and the entry still names it),
   flips back to VALID and pumps the mailbox locally.

Device tier — batched: the packed ShardMoves are fenced against the
engine's pending queue (a queued invocation caches its (shard, slot); its
key must not move under it), then applied as ONE functional gather+scatter
over the table (``ShardedActorTable.move_rows``) with the directory maps
re-pointed only after the device copy commits.
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..core.ids import GrainId, type_code_of
from ..core.message import Category
from ..observability.stats import REBALANCE_STATS
from ..runtime.activation import ActivationState
from ..runtime.grain import StatefulGrain

log = logging.getLogger("orleans.rebalance")

REBALANCE_TARGET = "RebalanceTarget"

__all__ = ["MigrationExecutor", "REBALANCE_TARGET"]


class MigrationExecutor:
    def __init__(self, silo):
        self.silo = silo

    # ------------------------------------------------------------------
    # Host tier
    # ------------------------------------------------------------------
    async def migrate_activation(self, act, dest) -> bool:
        """Live-migrate one local activation to silo ``dest``. Returns
        True on commit; False leaves the activation serving locally (or,
        if a racing re-creation won the directory while we were fenced,
        completes the deactivation instead). Each leg records a
        "migration" span when the silo traces, so rebalance cost shows on
        the same timeline as the request latency it perturbs."""
        tracer = self.silo.tracer
        if tracer is None or not tracer.sample():
            return await self._migrate_activation(act, dest)
        span = tracer.open(f"migrate {act.grain_id}", "migration",
                           tracer.new_trace_id(), None)
        committed = False
        try:
            committed = await self._migrate_activation(act, dest)
            return committed
        finally:
            tracer.close(span, dest=str(dest), committed=committed)

    async def _migrate_activation(self, act, dest) -> bool:
        silo = self.silo
        if act.state != ActivationState.VALID or \
                act.grain_id.is_system_target() or act.is_stateless_worker:
            return False
        act.state = ActivationState.DEACTIVATING  # fence: arrivals park
        deadline = time.monotonic() + silo.config.deactivation_timeout
        while act.running and time.monotonic() < deadline:
            await asyncio.sleep(0.005)
        if act.running:
            # a turn would not drain: the state is still being written —
            # abort before anything was copied
            self._rollback_local(act)
            silo.stats.increment(REBALANCE_STATS["rolled_back"])
            return False
        if act.timers:
            # re-check AFTER the drain, not just at plan time: a turn that
            # ran between planning and the fence may have armed a timer,
            # and committing would silently kill it (timer continuity
            # across a move is a ROADMAP follow-on)
            self._rollback_local(act)
            silo.stats.increment(REBALANCE_STATS["rolled_back"])
            return False
        state_payload = act.grain_instance.state \
            if isinstance(act.grain_instance, StatefulGrain) else None
        try:
            target = GrainId.system_target(
                type_code_of(REBALANCE_TARGET), dest)
            from .service import RebalanceTarget
            accepted = await silo.runtime_client.send_request(
                target_grain=target, grain_class=RebalanceTarget,
                interface_name=REBALANCE_TARGET,
                method_name="accept_activation",
                args=(act.grain_id, act.grain_class.__name__,
                      state_payload, act.activation_id),
                kwargs={}, target_silo=dest, category=Category.SYSTEM)
        except Exception as e:  # noqa: BLE001 — dest down/refused: roll back
            log.info("migration of %s to %s failed: %s",
                     act.grain_id, dest, e)
            silo.stats.increment(REBALANCE_STATS["refused"])
            await self._rollback(act)
            return False
        if not accepted:
            silo.stats.increment(REBALANCE_STATS["refused"])
            await self._rollback(act)
            return False
        # commit: the destination is VALID and owns the registration — the
        # local copy is now the duplicate (timer-free: the post-drain
        # re-check above refused anything with live timers). No unregister
        # (that would drop the DESTINATION's entry).
        silo.catalog._destroy(act)
        silo.stats.increment("catalog.activations.migrated_out")
        self._redispatch_mailbox(act)
        return True

    async def _rollback(self, act) -> None:
        """Transfer failed: take the registration back (first-wins; the
        entry normally still names us — a failed rehydrate surrendered any
        claim it briefly held) and resume serving."""
        silo = self.silo
        winner = None
        try:
            winner = await silo.locator.register(act.address)
        except Exception:  # noqa: BLE001 — owner unreachable: serve on;
            # the registration was never replaced
            pass
        if winner is not None and winner.activation != act.activation_id:
            # a racing re-creation registered while we were fenced: our
            # copy is the duplicate now — finish as a deactivation and
            # bounce the mailbox to the winner
            act.stop_timers()
            silo.catalog._destroy(act)
            self._redispatch_mailbox(act)
            silo.stats.increment(REBALANCE_STATS["rolled_back"])
            return
        self._rollback_local(act)
        silo.stats.increment(REBALANCE_STATS["rolled_back"])

    def _rollback_local(self, act) -> None:
        act.state = ActivationState.VALID
        self.silo.dispatcher.run_message_pump(act)

    def _redispatch_mailbox(self, act) -> None:
        """Re-address everything that parked behind the fence. Internal
        timer turns die with the local copy (same rule as Catalog
        deactivation: re-dispatching would resurrect a callback bound to
        the destroyed instance)."""
        for m in act.waiting:
            if m.method_name == "__timer__":
                _, done = m.body
                if done is not None and not done.done():
                    done.cancel()
                continue
            m.target_silo = None
            m.target_activation = None
            self.silo.dispatcher.send_message(m)
        act.waiting.clear()

    # ------------------------------------------------------------------
    # Device tier
    # ------------------------------------------------------------------
    def execute_shard_moves(self, moves) -> int:
        """Apply one class's packed shard moves on the local vector
        runtime. Runs synchronously on the event loop — between the fence
        check and the table commit there is no await, so no new pending
        entry can appear for a moving key mid-flight."""
        rt = self.silo.vector
        if rt is None:
            return 0
        tbl = rt.tables.get(moves.cls)
        if tbl is None:
            return 0
        fenced = rt.pending_key_hashes(moves.cls)
        keep = [i for i, k in enumerate(moves.keys) if int(k) not in fenced]
        if not keep:
            return 0
        tracer = self.silo.tracer
        span = None
        if tracer is not None and tracer.sample():
            span = tracer.open(f"shard_moves {moves.cls.__name__}",
                               "migration", tracer.new_trace_id(), None)
        try:
            n = tbl.move_rows(moves.keys[keep], moves.dest_shards[keep])
            if span is not None:
                tracer.close(span, rows=n)
            return n
        except Exception:  # noqa: BLE001 — move_rows only commits its
            # bookkeeping after the device copy succeeds, so a failure
            # here left the table untouched; count and carry on
            log.exception("shard move failed for %s", moves.cls.__name__)
            self.silo.stats.increment(REBALANCE_STATS["rolled_back"])
            if span is not None:
                tracer.close(span, rows=0, error=True)
            return 0
