"""Rebalancer service: the telemetry → plan → execute loop, per silo.

The runtime piece that turns static placement + the offline
``reshard_dense`` snapshot path into a self-balancing system: every
``rebalance_period`` seconds each silo reads the cluster load view
(DeploymentLoadPublisher broadcasts, extended with queue depth and
device-shard heat by ``rebalance.telemetry``), asks the planner for a
budget-bounded migration plan, and executes it live — host activations
over the fabric, device rows as batched shard copies. Per-round outcomes
land in ``observability.stats`` under the ``REBALANCE_STATS`` names.

``add_rebalancer(builder)`` is the hosting hook; the loop only runs when
``rebalance_period > 0`` (config.RebalanceOptions), and a silo with the
service installed always hosts the RebalanceTarget so it can RECEIVE
migrations even when its own loop is disabled.
"""

from __future__ import annotations

import asyncio
import logging

from ..observability.stats import REBALANCE_STATS
from .executor import REBALANCE_TARGET, MigrationExecutor
from .planner import RebalancePlanner

log = logging.getLogger("orleans.rebalance")

__all__ = ["Rebalancer", "RebalanceTarget", "add_rebalancer",
           "REBALANCE_TARGET"]


class RebalanceTarget:
    """Per-silo system target: the receive half of a live migration."""

    _activation = None

    def __init__(self, silo):
        self.silo = silo

    async def accept_activation(self, grain_id, class_name: str,
                                state_payload, prev_activation) -> bool:
        """Rehydrate a migrating activation here. Raises (failing the
        migration RPC, so the source rolls back) rather than returning
        False for every refusal — the source treats both the same, but an
        exception carries the reason."""
        from ..core.errors import OrleansError

        if self.silo.status != "Running":
            raise OrleansError(
                f"silo {self.silo.silo_address} is {self.silo.status}; "
                "not accepting migrations")
        grain_class = self.silo.registry.resolve(class_name)
        if grain_class is None:
            raise OrleansError(
                f"grain class {class_name!r} is not registered on "
                f"{self.silo.silo_address}")
        await self.silo.catalog.rehydrate_activation(
            grain_id, grain_class, state_payload, prev_activation)
        return True


class Rebalancer:
    """Periodic plan/execute loop (one per silo)."""

    def __init__(self, silo, period: float | None = None,
                 budget: int | None = None,
                 imbalance_ratio: float | None = None):
        self.silo = silo
        self.period = period if period is not None \
            else silo.config.rebalance_period
        self.planner = RebalancePlanner(silo, budget=budget,
                                        imbalance_ratio=imbalance_ratio)
        self.executor = MigrationExecutor(silo)
        self.rounds = 0
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        # the device tier only pays for telemetry once a consumer exists:
        # a receive-only rebalancer (period 0, hosting the target so peers
        # can migrate IN) must not tax every tick with counters nobody
        # resets — drivers of manual rounds enable tracking themselves
        if self.period > 0:
            if self.silo.vector is not None:
                self.silo.vector.enable_load_tracking()
            if self._task is None:
                self._task = asyncio.get_running_loop().create_task(
                    self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.period)
            if self.silo.status != "Running":
                continue
            try:
                await self.run_round()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — next round retries
                log.exception("rebalance round failed")

    def _cluster_device_hot_ratio(self) -> float:
        """Hottest silo's per-class device hit total over the cluster mean
        — the consumer of the ``vector_hits`` field every silo broadcasts
        in its load report. Intra-silo shard skew is handled by this
        round's shard moves; a ratio persistently above the hysteresis
        here means one SILO's device tier runs hot, which only cross-silo
        row migration (ROADMAP follow-on) can fix — surface it so
        operators see the gap."""
        from .telemetry import vector_shard_hits

        totals: dict[str, list[float]] = {}
        for cls_name, hits in vector_shard_hits(self.silo).items():
            totals.setdefault(cls_name, []).append(float(sum(hits)))
        publisher = getattr(self.silo, "load_publisher", None)
        if publisher is not None:
            me = self.silo.silo_address
            for peer in self.silo.locator.alive_list:
                if peer == me:
                    continue
                report = publisher.report_of(peer)
                for cls_name, hits in (report or {}).get(
                        "vector_hits", {}).items():
                    totals.setdefault(cls_name, []).append(float(sum(hits)))
        ratio = 0.0
        for per_silo in totals.values():
            mean = sum(per_silo) / len(per_silo)
            if mean > 0:
                ratio = max(ratio, max(per_silo) / mean)
        return ratio

    async def run_round(self) -> dict:
        """One telemetry → plan → execute round. Returns the outcome
        (also mirrored into the stats registry)."""
        stats = self.silo.stats
        plan = self.planner.plan()
        stats.set_gauge(REBALANCE_STATS["device_hot_ratio"],
                        self._cluster_device_hot_ratio())
        rt = self.silo.vector
        if rt is not None:
            # reset immediately after planning, even on a no-op round:
            # every round plans against the load since the previous one,
            # and an always-balanced cluster must not accumulate the
            # int32 counters toward overflow
            for tbl in rt.tables.values():
                tbl.reset_hits()
        self.rounds += 1
        stats.increment(REBALANCE_STATS["rounds"])
        stats.set_gauge(REBALANCE_STATS["last_imbalance"], plan.imbalance)
        outcome = {"planned": plan.total, "migrated": 0, "rows_moved": 0,
                   "imbalance": plan.imbalance}
        if not plan:
            stats.set_gauge(REBALANCE_STATS["last_moved"], 0)
            return outcome
        stats.increment(REBALANCE_STATS["planned"], plan.total)
        # device moves first: synchronous, and draining the hot shard
        # cheapens any host moves that follow in the same round
        dropped = 0
        for moves in plan.shard_moves:
            outcome["rows_moved"] += self.executor.execute_shard_moves(moves)
            dropped += moves.dropped
        if dropped:
            # truncation must be visible: a round that planned more than
            # the budget admits reports how much heat it left behind
            stats.increment(REBALANCE_STATS["dropped"], dropped)
            outcome["dropped"] = dropped
        for mv in plan.activation_moves:
            if await self.executor.migrate_activation(mv.act, mv.dest):
                outcome["migrated"] += 1
        stats.increment(REBALANCE_STATS["migrated"], outcome["migrated"])
        stats.increment(REBALANCE_STATS["rows_moved"], outcome["rows_moved"])
        stats.set_gauge(REBALANCE_STATS["last_moved"],
                        outcome["migrated"] + outcome["rows_moved"])
        if outcome["migrated"] or outcome["rows_moved"]:
            log.info("rebalance round %d: %d activations, %d device rows "
                     "moved (imbalance %.2f)", self.rounds,
                     outcome["migrated"], outcome["rows_moved"],
                     plan.imbalance)
        return outcome


def add_rebalancer(builder, period: float | None = None,
                   budget: int | None = None,
                   imbalance_ratio: float | None = None):
    """Install the rebalancer on a SiloBuilder. Explicit arguments
    override the silo config's ``rebalance_*`` knobs (which come from
    ``config.RebalanceOptions``); with neither, the target is hosted but
    the loop stays off (period 0)."""

    def install(silo) -> None:
        target = RebalanceTarget(silo)
        silo.register_system_target(target, REBALANCE_TARGET)
        silo.rebalancer = Rebalancer(silo, period=period, budget=budget,
                                     imbalance_ratio=imbalance_ratio)
        from ..runtime.silo import ServiceLifecycleStage

        silo.subscribe_lifecycle(
            ServiceLifecycleStage.APPLICATION_SERVICES,
            silo.rebalancer.start, silo.rebalancer.stop)

    return builder.configure(install)
