"""Cluster management + control surface (reference L13,
src/Orleans.Runtime/Core/ManagementGrain.cs, Silo/SiloControl.cs,
src/OrleansManager/)."""

from .control import SiloControl, add_management
from .grain import ManagementGrain
from .load_publisher import DeploymentLoadPublisher

__all__ = ["ManagementGrain", "SiloControl", "DeploymentLoadPublisher",
           "add_management"]
