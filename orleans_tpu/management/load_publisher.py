"""DeploymentLoadPublisher: periodic per-silo load broadcast.

Re-design of /root/reference/src/Orleans.Runtime/Placement/
DeploymentLoadPublisher.cs:17 (publish :85): each silo periodically pushes
its runtime stats (activation count, queue depths) to every peer; placement
directors read the freshest view. The in-proc fabric shortcut (reading the
peer catalog directly) remains the fallback when no publisher runs.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import TYPE_CHECKING

from ..core.ids import GrainId, SiloAddress, type_code_of
from ..core.message import Category

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.management.load")

LOAD_TARGET = "LoadPublisherTarget"

__all__ = ["DeploymentLoadPublisher"]


class _LoadTarget:
    """System target receiving peer load reports."""

    _activation = None

    def __init__(self, publisher: "DeploymentLoadPublisher"):
        self.publisher = publisher

    async def load_report(self, silo: SiloAddress, report: dict) -> None:
        self.publisher.view[silo] = report


class DeploymentLoadPublisher:
    """Publishes this silo's load; aggregates peers' reports in ``view``."""

    def __init__(self, silo: "Silo", period: float = 1.0):
        self.silo = silo
        self.period = period
        self.view: dict[SiloAddress, dict] = {}
        self.target = _LoadTarget(self)
        silo.register_system_target(self.target, LOAD_TARGET)
        self._task: asyncio.Task | None = None

    def load_of(self, silo: SiloAddress) -> int | None:
        report = self.report_of(silo)
        # stale/absent: None — caller falls back to the fabric read
        return None if report is None else report["activation_count"]

    def report_of(self, silo: SiloAddress) -> dict | None:
        """Full freshest report for a peer (activation count, queue depth,
        per-class device-shard heat) — the rebalance planner's view."""
        report = self.view.get(silo)
        if report is None or time.time() - report["ts"] > 10 * self.period:
            return None
        return report

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                self._publish()
            except Exception:  # noqa: BLE001
                log.exception("load publish failed")
            await asyncio.sleep(self.period)

    def _publish(self) -> None:
        # the extended load report (activation count + queue depth +
        # per-class device-shard heat) comes from rebalance.telemetry so
        # planners on every peer see one consistent schema
        from ..rebalance.telemetry import load_report

        report = load_report(self.silo)
        report["ts"] = time.time()
        me = self.silo.silo_address
        self.view[me] = report
        for peer in self.silo.locator.alive_list:
            if peer == me:
                continue
            gid = GrainId.system_target(type_code_of(LOAD_TARGET), peer)
            try:
                self.silo.runtime_client.send_request(
                    target_grain=gid, grain_class=_LoadTarget,
                    interface_name=LOAD_TARGET, method_name="load_report",
                    args=(me, report), kwargs={}, is_one_way=True,
                    target_silo=peer, category=Category.SYSTEM)
            except Exception:  # noqa: BLE001
                pass
