"""SiloControl: the per-silo management command surface.

Re-design of /root/reference/src/Orleans.Runtime/Silo/SiloControl.cs:214 —
a system target exposing runtime stats, activation enumeration/counts,
forced collection, version-strategy updates, and the activation debug dump
(Silo.GetDebugDump, Silo.cs:825-856). ManagementGrain fans out to these.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ..runtime.silo import Silo

SILO_CONTROL = "SiloControl"

__all__ = ["SiloControl", "add_management"]


class SiloControl:
    """Per-silo control system target."""

    _activation = None

    def __init__(self, silo: "Silo"):
        self.silo = silo

    def _vector_stats(self) -> dict:
        """Device-tier runtime stats (no reference analog — the vector
        tier's management lens): per-class activation counts + tick/message
        totals."""
        rt = self.silo.vector
        if rt is None:
            return {}
        return {
            "ticks": rt.ticks,
            "messages_processed": rt.messages_processed,
            "exchange_lanes": rt.exchange_lanes,
            "conflicts_deferred": rt.conflicts_deferred,
            "queue_depth": rt.queue_depth(),
            "classes": {cls.__name__: tbl.active_count()
                        for cls, tbl in rt.tables.items()},
        }

    async def ctl_runtime_stats(self) -> dict:
        """Per-silo stats snapshot (SiloRuntimeStatistics)."""
        return {
            "silo": str(self.silo.silo_address),
            "status": self.silo.status,
            "activation_count": self.silo.catalog.activation_count(),
            "stats": self.silo.stats.snapshot(),
            "vector": self._vector_stats(),
        }

    async def ctl_activation_count(self) -> int:
        n = self.silo.catalog.activation_count()
        if self.silo.vector is not None:
            n += sum(t.active_count()
                     for t in self.silo.vector.tables.values())
        return n

    async def ctl_grain_stats(self) -> dict[str, int]:
        """Activation count per grain class (GetSimpleGrainStatistics) —
        both tiers."""
        counts: dict[str, int] = {}
        for act in self.silo.catalog.by_activation.values():
            if act.grain_id.is_system_target():
                continue  # app grains only, matching GetSimpleGrainStatistics
            name = act.grain_class.__name__ if act.grain_class else "?"
            counts[name] = counts.get(name, 0) + 1
        for cls, n in self._vector_stats().get("classes", {}).items():
            counts[cls] = counts.get(cls, 0) + n
        return counts

    async def ctl_force_collection(self, age_seconds: float = 0.0) -> int:
        """Deactivate idle activations older than ``age_seconds``
        (ForceActivationCollection)."""
        return await self.silo.catalog.collect_idle(max_age=age_seconds)

    async def ctl_debug_dump(self) -> list[dict]:
        """All activations with mailbox depth + state (GetDebugDump)."""
        out = []
        for act in self.silo.catalog.by_activation.values():
            out.append({
                "grain": str(act.grain_id),
                "activation": str(act.activation_id),
                "class": act.grain_class.__name__ if act.grain_class else "?",
                "state": str(act.state),
                "waiting": len(act.waiting),
                "running": len(act.running),
            })
        return out

    async def ctl_set_compatibility_strategy(
            self, compat: str | None = None,
            selector: str | None = None) -> bool:
        """SetCompatibilityStrategy / SetSelectorStrategy."""
        self.silo.locator.versions.set_strategy(compat, selector)
        return True

    async def ctl_cache_invalidate(self, grain_id) -> bool:
        self.silo.locator.invalidate_cache(grain_id)
        return True

    # -- distributed tracing (observability.tracing) ----------------------
    async def ctl_trace_spans(self, trace_id: int | None = None,
                              limit: int | None = None,
                              pull: bool = False) -> list[dict]:
        """This silo's collected spans (optionally one trace); [] when
        tracing is disabled. The ManagementGrain merges these
        cluster-wide for breakdowns and Perfetto export. Reads are pure —
        in tail mode a trace_id query also shows that trace's pending
        (undecided) legs without touching their fate.

        ``pull=True`` is the retention-propagation form (the rooting
        silo's `Silo._pull_trace_legs` sets it when it RETAINS a trace):
        this silo's pending legs of that trace are handed off —
        counted kept/pulled here, stored and exported by the puller —
        instead of quietly expiring. Diagnostic callers must leave it
        False so polling a live trace never mutates retention state."""
        tracer = self.silo.tracer
        if tracer is None:
            return []
        if pull and trace_id is not None and tracer.tail:
            return tracer.pull(trace_id, limit)
        return tracer.snapshot(trace_id, limit)

    async def ctl_retention_stats(self) -> dict:
        """Tail-retention + export counters (kept/dropped/pulled/buffered,
        OTLP exported/export_dropped); {} when tracing is disabled."""
        tracer = self.silo.tracer
        return {} if tracer is None else tracer.retention_stats()

    async def ctl_trace_breakdown(self, trace_id: int | None = None) -> dict:
        """Critical-path breakdown over THIS silo's spans (per-silo view;
        the cluster-wide one lives on the ManagementGrain)."""
        from ..observability.tracing import critical_path_breakdown
        return critical_path_breakdown(await self.ctl_trace_spans(trace_id))

    async def ctl_metrics(self) -> dict:
        """Full metrics payload for the cluster merge
        (ManagementGrain.get_cluster_metrics): the stats-registry snapshot
        (counters/gauges/histograms-with-buckets) plus, when the sampler
        is installed, the time-windowed queue/backpressure series
        summaries."""
        snap = self.silo.stats.snapshot()
        # the config NAME, not the address: one silo identity across the
        # metrics surface (OTLP push data points, Prometheus labels, span
        # silo attrs all use it), so dashboards join without a mapping
        snap["silo"] = self.silo.config.name
        snap["address"] = str(self.silo.silo_address)
        sampler = self.silo.metrics
        if sampler is not None:
            snap["windows"] = sampler.window_snapshot()
        return snap

    async def ctl_loop_profile(self, windows: int = 20,
                               snapshots: bool = True) -> dict:
        """Host-loop occupancy profile + flight recorder
        (observability.profiling.LoopProfiler): cumulative per-category
        seconds/shares of loop wall time (summing to ~1.0 incl. idle),
        the last ``windows`` per-window slices with their top-K slowest
        callbacks, and — when ``snapshots`` — the anomaly-triggered
        flight-recorder snapshots. {} when profiling is disabled. NOTE:
        co-hosted silos on one event loop share one profiler (occupancy
        is a loop property), so their payloads are views of the same
        loop."""
        import os
        lp = self.silo.loop_prof
        if lp is None:
            return {}
        out = lp.profile(windows, snapshots=snapshots)
        out["silo"] = self.silo.config.name
        # pid-stamp the payload AND each flight-recorder snapshot: under
        # worker_procs>1 every process profiles its own loop, and a
        # cluster merge that pools anomaly snapshots must still name the
        # process that tripped (copies — the recorder ring is live state)
        out["pid"] = os.getpid()
        if out.get("snapshots"):
            out["snapshots"] = [dict(s, pid=os.getpid())
                                for s in out["snapshots"]]
        pool = self.silo.ingress_pool
        if pool is not None:
            # multi-loop silo: the profiler installs PER LOOP, so each
            # ingress shard carries its own occupancy profile — surfaced
            # beside the main loop's for per-loop attribution (the
            # ctl_loop_profile aggregation the tentpole design promised)
            out["ingress_loops"] = await pool.loop_profiles(
                windows=min(windows, 8))
        return out

    async def ctl_critical_path(self) -> dict:
        """Per-silo critical-path leaf: loop-profiler occupancy seconds
        over its wall, the ingest / shm-ring / egress stage histograms
        (bucket-bearing summaries, so the cluster merge folds them
        losslessly via Histogram.merge), and the device-tick span count/
        seconds from the tracer's synthetic device trace.
        ManagementGrain.get_cluster_critical_path merges one of these
        per process — owner and every shm worker — into the cluster
        request waterfall."""
        import os
        from ..observability.stats import (EGRESS_STATS, INGEST_STATS,
                                           RING_STATS)
        out: dict = {"silo": self.silo.config.name, "pid": os.getpid()}
        lp = self.silo.loop_prof
        if lp is not None:
            prof = lp.profile(0, snapshots=False)
            out["loop"] = {"wall_s": prof["wall_s"],
                           "seconds": prof["seconds"]}
        hists = self.silo.stats.histograms
        stages: dict[str, dict] = {}
        for group, table in (("ingest", INGEST_STATS),
                             ("ring", RING_STATS),
                             ("egress", EGRESS_STATS)):
            g = {key: hists[name].summary()
                 for key, name in table.items() if name in hists}
            if g:
                stages[group] = g
        out["stages"] = stages
        tracer = self.silo.tracer
        if tracer is not None:
            dev = tracer.snapshot(tracer.device_trace_id)
            out["device_spans"] = {
                "count": len(dev),
                "seconds": round(sum(s["duration"] for s in dev), 6),
            }
        return out

    async def ctl_slo(self) -> dict:
        """This silo's SLO verdicts (observability.slo.SloMonitor.status:
        per-objective met/breached, multi-window burn rates, budget
        burned) plus the top call sites as the breach drill-down — the
        per-silo leaf of ManagementGrain.get_cluster_slo's
        worst-burn-wins merge. {} when the SLO engine is disabled."""
        mon = self.silo.slo
        if mon is None:
            return {}
        out = mon.status()
        cs = self.silo.call_sites
        if cs is not None:
            # which grain methods are hot/slow/erroring behind the burn
            out["call_sites"] = cs.top(10)
        led = self.silo.ledger
        if led is not None:
            # WHO is burning the budget: the cost ledger's heaviest
            # keys, tenant-annotated (the breach drill-down)
            out["ledger"] = led.top_burners(10)
        return out

    async def ctl_call_sites(self, k: int = 20) -> dict:
        """Per-(grain_class, method) call-site latency/error table
        (observability.stats.CallSiteStats.snapshot, top-``k`` by summed
        turn seconds); {} when metrics are disabled."""
        cs = self.silo.call_sites
        return {} if cs is None else cs.snapshot(k)

    async def ctl_ledger(self, k: int = 10) -> dict:
        """This silo's cost-attribution ledger snapshot
        (observability.ledger.CostLedger.snapshot: exact per-method
        turn/device/wire/stream tables plus the top-``k`` key/tenant
        sketches) — the per-silo leaf of
        ManagementGrain.get_cluster_ledger's deterministic merge. {}
        when ``ledger_enabled`` is off."""
        led = self.silo.ledger
        return {} if led is None else led.snapshot(k)

    async def ctl_workers(self) -> dict:
        """Multi-process silo topology (runtime.multiproc): per-worker
        pid/liveness/internal-endpoint, the staging/response ring
        cumulative counters (single-writer, so this read is torn-free —
        pushed == drained after a clean drain), and each worker's live
        client-route count from the relay table (the accept-balance
        spread the multiproc floor asserts on). Per-worker DEEP stats
        need no special path: workers are full cluster-member silos, so
        the existing per-silo ``ctl_*`` RPCs reach them by address.
        ``{"worker_procs": 1}`` when this silo runs single-process."""
        sup = self.silo.workers
        if sup is None:
            return {"worker_procs": 1}
        return sup.describe()

    async def ctl_histogram(self, name: str) -> dict | None:
        """One named histogram's summary (with per-bucket counts so the
        ManagementGrain can merge silos losslessly); None if unknown."""
        h = self.silo.stats.histograms.get(name)
        return None if h is None else h.summary()

    async def ctl_multicluster_stamp(self) -> float | None:
        """This silo's view of the current multi-cluster configuration
        stamp (None = no config / no oracle) — the ManagementGrain's
        lagging-silo stability check reads this before injecting a new
        configuration."""
        oracle = getattr(self.silo, "multicluster", None)
        return oracle.config_stamp() if oracle is not None else None


def add_management(builder):
    """Install SiloControl + the management grain + the load publisher on a
    SiloBuilder."""
    from .grain import ManagementGrain
    from .load_publisher import DeploymentLoadPublisher

    builder.add_grains(ManagementGrain)

    def install(silo) -> None:
        control = SiloControl(silo)
        silo.register_system_target(control, SILO_CONTROL)
        silo.silo_control = control
        publisher = DeploymentLoadPublisher(silo)
        silo.load_publisher = publisher
        from ..runtime.silo import ServiceLifecycleStage
        silo.subscribe_lifecycle(
            ServiceLifecycleStage.RUNTIME_GRAIN_SERVICES,
            publisher.start, publisher.stop)

    return builder.configure(install)
