"""ManagementGrain: cluster-wide queries and controls.

Re-design of /root/reference/src/Orleans.Runtime/Core/ManagementGrain.cs:52-424
(GetHosts, GetRuntimeStatistics, GetSimpleGrainStatistics, GetTotalActivationCount,
ForceActivationCollection, SetCompatibilityStrategy, FindLaggingSilos :424) —
an ordinary grain fanning out to each silo's SiloControl system target.
"""

from __future__ import annotations

import asyncio

from ..core.ids import GrainId, SiloAddress, type_code_of
from ..core.message import Category
from ..runtime.grain import Grain
from .control import SILO_CONTROL, SiloControl

__all__ = ["ManagementGrain"]


class ManagementGrain(Grain):
    """Singleton management grain (key 0 by convention)."""

    # -- fan-out helper --------------------------------------------------
    def _silos(self) -> list[SiloAddress]:
        return list(self._activation.runtime.locator.alive_list)

    def _control(self, silo: SiloAddress, method: str, *args, **kwargs):
        runtime = self._activation.runtime
        gid = GrainId.system_target(type_code_of(SILO_CONTROL), silo)
        return runtime.runtime_client.send_request(
            target_grain=gid, grain_class=SiloControl,
            interface_name=SILO_CONTROL, method_name=method,
            args=args, kwargs=kwargs, target_silo=silo,
            category=Category.SYSTEM)

    async def _fan_out(self, method: str, *args, **kwargs) -> dict:
        silos = self._silos()
        results = await asyncio.gather(
            *(self._control(s, method, *args, **kwargs) for s in silos),
            return_exceptions=True)
        return {str(s): r for s, r in zip(silos, results)
                if not isinstance(r, BaseException)}

    # -- queries (ManagementGrain.cs:52-231) ------------------------------
    async def get_hosts(self) -> dict[str, str]:
        """Silo → status map; reads the membership oracle when installed."""
        runtime = self._activation.runtime
        if runtime.membership is not None:
            out = {str(a): "Active" for a in runtime.membership.active}
            out.update({str(a): "Dead" for a in runtime.membership.dead})
            return out
        return {str(a): "Active" for a in runtime.locator.alive_list}

    async def get_runtime_statistics(self) -> dict:
        return await self._fan_out("ctl_runtime_stats")

    async def get_simple_grain_statistics(self) -> dict[str, int]:
        """Cluster-wide activation count per grain class."""
        per_silo = await self._fan_out("ctl_grain_stats")
        totals: dict[str, int] = {}
        for counts in per_silo.values():
            for name, n in counts.items():
                totals[name] = totals.get(name, 0) + n
        return totals

    async def get_total_activation_count(self) -> int:
        per_silo = await self._fan_out("ctl_activation_count")
        return sum(per_silo.values())

    async def get_debug_dump(self) -> dict:
        return await self._fan_out("ctl_debug_dump")

    # -- controls ---------------------------------------------------------
    async def force_activation_collection(self, age_seconds: float = 0.0
                                          ) -> int:
        per_silo = await self._fan_out("ctl_force_collection", age_seconds)
        return sum(per_silo.values())

    async def set_compatibility_strategy(self, compat: str | None = None,
                                         selector: str | None = None) -> None:
        await self._fan_out("ctl_set_compatibility_strategy", compat, selector)

    # -- distributed tracing (observability.tracing) ----------------------
    async def get_trace_spans(self, trace_id: int | None = None,
                              limit: int | None = None) -> list[dict]:
        """Cluster-wide span merge: every silo's collector, one list
        (client-process spans live in the client's own collector — the
        breakdown tolerates their absence by using the span extent)."""
        per_silo = await self._fan_out("ctl_trace_spans", trace_id, limit)
        return [s for spans in per_silo.values() for s in spans]

    async def get_trace_breakdown(self, trace_id: int | None = None) -> dict:
        """Critical-path breakdown for one trace (or everything buffered):
        queue / exec / network / directory / device / migration seconds
        and fractions of the trace extent, cluster-wide."""
        from ..observability.tracing import critical_path_breakdown
        return critical_path_breakdown(await self.get_trace_spans(trace_id))

    async def get_retention_stats(self) -> dict:
        """Cluster-wide tail-retention/export counters: per-silo snapshots
        plus summed totals (kept/dropped/pulled/buffered/exported/
        export_dropped) — the operator's answer to "is tail sampling
        keeping the right amount"."""
        per_silo = await self._fan_out("ctl_retention_stats")
        totals: dict[str, int] = {}
        for snap in per_silo.values():
            for k, v in snap.items():
                if isinstance(v, bool) or not isinstance(v, int):
                    continue
                totals[k] = totals.get(k, 0) + v
        return {"totals": totals, "per_silo": per_silo}

    async def get_cluster_metrics(self) -> dict:
        """Cluster-wide metrics merge over every silo's ``ctl_metrics``:
        counters and gauges sum across silos, histograms fold losslessly
        via their per-bucket counts (Histogram.merge), and the per-silo
        snapshots (including sampler window summaries) ride along for
        drill-down — one call answers both "what is the cluster doing"
        and "which silo is the outlier"."""
        from ..observability.stats import Histogram
        per_silo = await self._fan_out("ctl_metrics")
        counters: dict[str, int] = {}
        gauges: dict[str, float] = {}
        hists: dict[str, Histogram] = {}
        for snap in per_silo.values():
            for k, v in snap.get("counters", {}).items():
                counters[k] = counters.get(k, 0) + v
            for k, v in snap.get("gauges", {}).items():
                gauges[k] = gauges.get(k, 0.0) + float(v)
            for k, h in snap.get("histograms", {}).items():
                merged = hists.get(k)
                if merged is None:
                    hists[k] = Histogram.from_snapshot(h)
                else:
                    merged.merge(Histogram.from_snapshot(h))
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": {k: h.summary() for k, h in hists.items()},
            "per_silo": per_silo,
        }

    async def get_cluster_loop_profile(self, windows: int = 20) -> dict:
        """Cluster-wide host-loop occupancy merge over every silo's
        ``ctl_loop_profile``: per-category loop seconds sum across
        silos (shares recomputed over the summed wall), flight-recorder
        trigger counts sum, and the per-silo payloads — windows, top-K
        slow callbacks, and anomaly snapshots — ride along for
        drill-down. One call answers "what occupies the cluster's loops"
        and "which silo's loop is the outlier". Caveat: silos co-hosted
        on ONE event loop share one profiler, so the merged totals count
        that loop once per resident silo — read per_silo for the truth
        on shared-loop test clusters."""
        per_silo = await self._fan_out("ctl_loop_profile", windows)
        seconds: dict[str, float] = {}
        triggers: dict[str, int] = {}
        snapshots = 0
        for snap in per_silo.values():
            for k, v in (snap.get("seconds") or {}).items():
                seconds[k] = seconds.get(k, 0.0) + float(v)
            for k, v in (snap.get("triggers") or {}).items():
                triggers[k] = triggers.get(k, 0) + int(v)
            snapshots += len(snap.get("snapshots") or ())
        wall = sum(seconds.values())
        return {
            "wall_s": round(wall, 6),
            "seconds": {k: round(v, 6) for k, v in seconds.items()},
            "shares": {k: round(v / wall, 4)
                       for k, v in seconds.items()} if wall else {},
            "triggers": triggers,
            "snapshot_count": snapshots,
            "per_silo": per_silo,
        }

    async def get_cluster_critical_path(self) -> dict:
        """Cluster-wide request waterfall over every process's
        ``ctl_critical_path``: loop-profiler occupancy seconds sum per
        category across processes (owner and shm workers alike — workers
        are cluster members, so the fan-out reaches them by address) with
        shares recomputed over the summed wall, so the shares sum to
        ~1.0 of measured loop wall by construction; ingest / shm-ring /
        egress stage histograms fold losslessly via their per-bucket
        counts; device-tick span seconds sum. ``processes`` carries each
        per-process leaf (silo name + pid) for drill-down — the answer to
        "where does a cross-process request spend its wall time"."""
        from ..observability.stats import Histogram
        per_silo = await self._fan_out("ctl_critical_path")
        seconds: dict[str, float] = {}
        wall = 0.0
        stage_h: dict[str, dict[str, Histogram]] = {}
        dev_count, dev_seconds = 0, 0.0
        for snap in per_silo.values():
            loop = snap.get("loop")
            if loop:
                wall += float(loop.get("wall_s", 0.0))
                for k, v in (loop.get("seconds") or {}).items():
                    seconds[k] = seconds.get(k, 0.0) + float(v)
            for group, table in (snap.get("stages") or {}).items():
                acc = stage_h.setdefault(group, {})
                for key, h in table.items():
                    merged = acc.get(key)
                    if merged is None:
                        acc[key] = Histogram.from_snapshot(h)
                    else:
                        merged.merge(Histogram.from_snapshot(h))
            dev = snap.get("device_spans")
            if dev:
                dev_count += int(dev.get("count", 0))
                dev_seconds += float(dev.get("seconds", 0.0))
        return {
            "wall_s": round(wall, 6),
            "seconds": {k: round(v, 6) for k, v in seconds.items()},
            "shares": {k: round(v / wall, 4)
                       for k, v in seconds.items()} if wall else {},
            "stages": {group: {key: h.summary() for key, h in acc.items()}
                       for group, acc in stage_h.items()},
            "device_spans": {"count": dev_count,
                             "seconds": round(dev_seconds, 6)},
            "processes": per_silo,
        }

    async def get_cluster_slo(self) -> dict:
        """Cluster-wide SLO rollup over every silo's ``ctl_slo``:
        per-objective **worst-burn-wins** merge — burn rates and budget
        burned take the cluster max (an SLO is breached anywhere ⇒
        breached, and the worst silo defines how fast the budget dies),
        good/bad event counts sum, and ``worst_silo`` names the max-burn
        silo so a breach drills straight down to its per-silo payload
        (burn state + hottest call sites) riding in ``per_silo``. One
        call answers "is the cluster meeting its SLOs" and "which silo
        and which grain methods are killing it"."""
        per_silo = await self._fan_out("ctl_slo")
        merged: dict[str, dict] = {}
        total_breaches = 0
        for addr, snap in per_silo.items():
            if not snap:
                continue  # SLO engine disabled on that silo
            total_breaches += snap.get("breaches", 0)
            for name, obj in snap.get("objectives", {}).items():
                cur = merged.get(name)
                if cur is None:
                    cur = merged[name] = dict(obj)
                    # episode timelines are PER-SILO data: carrying the
                    # first-iterated silo's timestamps on the merged
                    # objective would attribute them cluster-wide — the
                    # drill-down lives in per_silo[worst_silo] instead
                    for k in ("breach_started", "breach_started_mono",
                              "first_breach_mono", "episodes"):
                        cur.pop(k, None)
                    cur["worst_silo"] = addr
                    continue
                if obj["burn_fast"] > cur["burn_fast"]:
                    cur["worst_silo"] = addr
                cur["burn_fast"] = max(cur["burn_fast"], obj["burn_fast"])
                cur["burn_slow"] = max(cur["burn_slow"], obj["burn_slow"])
                cur["budget_burned"] = max(cur["budget_burned"],
                                           obj["budget_burned"])
                cur["breached"] = cur["breached"] or obj["breached"]
                cur["met"] = cur["met"] and obj["met"]
                cur["breaches"] += obj["breaches"]
                cur["good"] += obj["good"]
                cur["bad"] += obj["bad"]
        return {
            "breached": any(o["breached"] for o in merged.values()),
            "breaches": total_breaches,
            "objectives": merged,
            "per_silo": per_silo,
        }

    async def get_cluster_call_sites(self, k: int = 20) -> list[dict]:
        """Cluster-wide per-(grain_class, method) call-site table: every
        silo's bounded top table folded (counts/errors/seconds sum, max
        takes the max), returned as the top-``k`` by summed turn seconds
        — the "which grain methods carry the cluster's load" read an SLO
        breach (or the future placement-policy compiler) drills into."""
        from ..observability.stats import CallSiteStats
        per_silo = await self._fan_out("ctl_call_sites", k)
        merged = CallSiteStats.merge(s for s in per_silo.values() if s)
        return CallSiteStats.format_top(merged["sites"], k)

    async def get_cluster_ledger(self, k: int = 10) -> dict:
        """Cluster-wide cost attribution over every silo's
        ``ctl_ledger``: exact per-method turn/device/wire/stream tables
        sum, the per-key and per-tenant space-saving sketches fold with
        CostLedger.merge's deterministic flat merge (silo count and merge
        order cannot change the answer — property-tested), and
        ``worst_burner``/``worst_tenant`` name the cluster's heaviest key
        and tenant from the merged ranking. Per-silo snapshots ride in
        ``per_silo`` for drill-down. One call answers "who is spending
        this cluster" — the drill-down an SLO breach (and the rebalance
        planner's host-tier candidates) starts from."""
        from ..observability.ledger import CostLedger
        per_silo = await self._fan_out("ctl_ledger", k)
        out = CostLedger.merge(s for s in per_silo.values() if s)
        out["per_silo"] = per_silo
        return out

    async def get_cluster_histogram(self, name: str) -> dict | None:
        """One named latency histogram aggregated across every silo
        (Histogram.merge over the per-bucket counts each SiloControl
        reports); None when no silo has observed it."""
        from ..observability.stats import Histogram
        per_silo = await self._fan_out("ctl_histogram", name)
        agg = None
        for snap in per_silo.values():
            if snap is not None:
                h = Histogram.from_snapshot(snap)
                agg = h if agg is None else agg.merge(h)
        return None if agg is None else agg.summary()

    # -- multi-cluster administration (ManagementGrain.cs:387-427) --------
    async def get_multicluster_configuration(self) -> dict | None:
        """The active admin-injected configuration, or None when the
        network runs zero-conf (gossip-governed membership)."""
        oracle = getattr(self._activation.runtime, "multicluster", None)
        if oracle is None:
            raise RuntimeError("multi-cluster is not configured on this "
                               "cluster (add_multicluster)")
        return oracle.active_config()

    async def inject_multicluster_configuration(
            self, clusters: list[str], comment: str = "",
            check_for_lagging_silos: bool = True) -> dict:
        """Replace the multi-cluster configuration
        (InjectMultiClusterConfiguration :392): verifies first — unless
        told not to — that every silo in THIS cluster has converged on
        the current configuration (an unreachable silo, or one still
        gossiping an older stamp, aborts the injection: injecting over a
        lagging silo could strand it on a config two generations back),
        then stamps + gossips the new cluster list. Clusters removed by
        the new configuration have their GSI entries demoted to Doubtful
        everywhere so grains re-home (see
        ClusterDirectoryGrain.demote_removed_owners)."""
        oracle = getattr(self._activation.runtime, "multicluster", None)
        if oracle is None:
            raise RuntimeError("multi-cluster is not configured on this "
                               "cluster (add_multicluster)")
        if check_for_lagging_silos:
            silos = self._silos()
            stamps = await self._fan_out("ctl_multicluster_stamp")
            cur = oracle.config_stamp()
            lagging = [s for s in map(str, silos)
                       if s not in stamps or stamps[s] != cur]
            if lagging:
                raise RuntimeError(
                    f"cannot inject multi-cluster configuration: silos "
                    f"not stabilized on the current configuration: "
                    f"{lagging}")
        return await oracle.inject_configuration(clusters, comment)

    async def find_lagging_silos(self, threshold: float = 0.5) -> list[str]:
        """Silos whose control surface responds slower than ``threshold``
        seconds (FindLaggingSilos :424)."""
        import time
        lagging = []
        for s in self._silos():
            t0 = time.monotonic()
            try:
                await self._control(s, "ctl_activation_count")
            except Exception:  # noqa: BLE001 — unreachable counts as lagging
                lagging.append(str(s))
                continue
            if time.monotonic() - t0 > threshold:
                lagging.append(str(s))
        return lagging
