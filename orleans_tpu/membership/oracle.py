"""Membership oracle: liveness protocol over a shared CAS table.

Re-design of /root/reference/src/Orleans.Runtime/MembershipService/
MembershipOracle.cs:12 — ring-successor probing (probe-target selection
:741-776), vote-based suspect→dead declaration (TryToSuspectOrKill:949),
IAmAlive heartbeat timestamps (:192-208), gossip as a "re-read the table"
hint (:322-336), and status fan-out to subscribers; view bookkeeping from
MembershipOracleData.cs.

Differences from the reference, by design:
  - probes ride the fabric as PING-category system-target requests (the
    Categories.Ping lane) instead of raw sockets, so network partitions
    injected at the fabric affect probes exactly like application traffic;
  - the oracle pushes its merged view to the silo's DistributedLocator
    (ring/directory) and to any ``subscribe``-d listener (reminder service,
    stream balancers) — the SiloStatusChangeNotification fan-out.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import TYPE_CHECKING, Callable

from ..core.ids import GrainId, SiloAddress, type_code_of
from ..core.message import Category
from .table import (
    MembershipEntry,
    MembershipTable,
    SiloStatus,
    TableSnapshot,
)

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.membership")

MEMBERSHIP_TARGET = "MembershipTarget"

# probe round-trip latency histogram (observability.stats.SLO_STATS):
# the PING-lane QoS objective's source
from ..observability.stats import SLO_STATS as _SLO  # noqa: E402

_PROBE_RTT = _SLO["probe_rtt"]

__all__ = ["MembershipOracle", "MembershipTarget", "join_cluster"]


class MembershipTarget:
    """Per-silo membership system target: the remote surface probed and
    gossiped to by peers (the Ping message handler + gossip receiver)."""

    _activation = None

    def __init__(self, oracle: "MembershipOracle"):
        self.oracle = oracle

    async def mbr_ping(self, from_silo: SiloAddress) -> bool:
        return True

    async def mbr_gossip(self, from_silo: SiloAddress) -> None:
        """Gossip is a hint to re-read the table (MembershipOracle.cs:322)."""
        self.oracle.schedule_refresh()


class MembershipOracle:
    """One oracle per silo; installed as ``silo.membership``."""

    def __init__(self, silo: "Silo", table: MembershipTable):
        self.silo = silo
        self.table = table
        cfg = silo.config
        self.probe_period = cfg.membership_probe_period
        self.probe_timeout = getattr(cfg, "membership_probe_timeout",
                                     cfg.membership_probe_period)
        self.missed_limit = cfg.membership_missed_probes_limit
        self.votes_needed = cfg.membership_votes_needed
        self.num_probed = getattr(cfg, "membership_num_probed", 3)
        self.iam_alive_period = getattr(cfg, "membership_iam_alive_period", 5.0)
        self.refresh_period = getattr(cfg, "membership_refresh_period", 5.0)
        self.vote_expiration = getattr(cfg, "membership_vote_expiration",
                                       10 * cfg.membership_probe_period)

        self.target = MembershipTarget(self)
        silo.register_system_target(self.target, MEMBERSHIP_TARGET)

        self.active: dict[SiloAddress, MembershipEntry] = {}
        self.dead: set[SiloAddress] = set()
        self.missed_probes: dict[SiloAddress, int] = {}
        self.declared_dead = False
        self._listeners: list[Callable[[list[SiloAddress], list[SiloAddress]], None]] = []
        self._tasks: list[asyncio.Task] = []
        self._refresh_wanted = asyncio.Event()
        self._stopped = False

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------
    async def become_active(self) -> None:
        """Join: CAS-insert own row as Active, adopt the table view, start
        the heartbeat/probe/refresh loops (BecomeActive, Silo.cs:478-488)."""
        now = time.time()
        entry = MembershipEntry(
            address=self.silo.silo_address, status=SiloStatus.ACTIVE,
            start_time=now, iam_alive_time=now)
        for _ in range(32):
            snap = await self.table.read_all()
            # prior incarnation at our endpoint must be declared dead first
            prior = [
                (e, tag) for e, tag in snap.entries
                if e.address.same_endpoint(self.silo.silo_address)
                and e.address.generation < self.silo.silo_address.generation
                and e.status != SiloStatus.DEAD
            ]
            if prior:
                e, tag = prior[0]
                e = e.copy()
                e.status = SiloStatus.DEAD
                await self.table.update_row(e, tag, snap.version.next())
                continue
            if await self.table.insert_row(entry, snap.version.next()):
                break
        else:
            raise RuntimeError("membership table join: CAS retry exhausted")
        await self.refresh(gossip=True)
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._iam_alive_loop()),
            loop.create_task(self._probe_loop()),
            loop.create_task(self._refresh_loop()),
        ]

    async def shutdown(self) -> None:
        """Graceful goodbye: own row → ShuttingDown → Dead, gossip out
        (Silo stop path)."""
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()
        for _ in range(16):
            snap = await self.table.read_all()
            mine = snap.get(self.silo.silo_address)
            if mine is None:
                break
            e, tag = mine
            e = e.copy()
            e.status = SiloStatus.DEAD
            if await self.table.update_row(e, tag, snap.version.next()):
                break
        self._gossip_all()

    def stop(self) -> None:
        """Hard stop (kill path): no table write, just cancel timers."""
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()

    # ------------------------------------------------------------------
    # View + fan-out
    # ------------------------------------------------------------------
    def subscribe(self, listener: Callable[[list[SiloAddress], list[SiloAddress]], None]) -> None:
        """SiloStatusChangeNotification subscription (Silo.cs:346-356)."""
        self._listeners.append(listener)

    def active_silos(self) -> list[SiloAddress]:
        return sorted(self.active, key=lambda a: a.uniform_hash)

    def is_dead(self, silo: SiloAddress) -> bool:
        return silo in self.dead

    def _process_snapshot(self, snap: TableSnapshot) -> None:
        new_active: dict[SiloAddress, MembershipEntry] = {}
        new_dead: set[SiloAddress] = set(self.dead)
        for e, _tag in snap.entries:
            if e.status == SiloStatus.ACTIVE:
                new_active[e.address] = e
            elif e.status == SiloStatus.DEAD:
                new_dead.add(e.address)
        for d in new_dead:
            new_active.pop(d, None)

        me = self.silo.silo_address
        if me in new_dead and not self.declared_dead:
            # the cluster voted us dead (partition survivor side won):
            # a dead silo must never come back — fast-kill ourselves
            # (MembershipOracle KillMyself semantics)
            self.declared_dead = True
            log.warning("%s: declared dead by the cluster; stopping", me)
            asyncio.ensure_future(self.silo.stop(graceful=False))

        died = [d for d in new_dead if d not in self.dead]
        changed = (set(new_active) != set(self.active)) or died
        self.active = new_active
        self.dead = new_dead
        for d in died:
            self.missed_probes.pop(d, None)
        if changed:
            alive = self.active_silos()
            if me not in alive and not self.declared_dead:
                alive = sorted({*alive, me}, key=lambda a: a.uniform_hash)
            self.silo.locator.on_membership_change(alive, died)
            for d in died:
                self.silo.runtime_client.break_outstanding_to_dead_silo(d)
            for listener in list(self._listeners):
                try:
                    listener(alive, died)
                except Exception:  # noqa: BLE001
                    log.exception("membership listener failed")

    async def refresh(self, gossip: bool = False) -> None:
        snap = await self.table.read_all()
        self._process_snapshot(snap)
        if gossip:
            self._gossip_all()

    def schedule_refresh(self) -> None:
        self._refresh_wanted.set()

    # ------------------------------------------------------------------
    # Heartbeats + probing
    # ------------------------------------------------------------------
    async def _iam_alive_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(self.iam_alive_period)
            try:
                await self.table.update_iam_alive(
                    self.silo.silo_address, time.time())
            except Exception:  # noqa: BLE001
                log.exception("IAmAlive update failed")

    async def _refresh_loop(self) -> None:
        while not self._stopped:
            try:
                await asyncio.wait_for(self._refresh_wanted.wait(),
                                       timeout=self.refresh_period)
            except asyncio.TimeoutError:
                pass
            self._refresh_wanted.clear()
            try:
                await self.refresh()
            except Exception:  # noqa: BLE001
                log.exception("membership refresh failed")

    def _probe_targets(self) -> list[SiloAddress]:
        """Ring successors of this silo (probe-target selection,
        MembershipOracle.cs:741-776)."""
        ring = self.active_silos()
        me = self.silo.silo_address
        if me not in ring:
            return []
        i = ring.index(me)
        succ = [ring[(i + k) % len(ring)] for k in range(1, len(ring))]
        return succ[: self.num_probed]

    async def _probe_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(self.probe_period)
            targets = self._probe_targets()
            await asyncio.gather(
                *(self._probe_one(t) for t in targets),
                return_exceptions=True)

    async def _probe_one(self, target: SiloAddress) -> None:
        gid = GrainId.system_target(type_code_of(MEMBERSHIP_TARGET), target)
        t0 = time.monotonic()
        try:
            fut = self.silo.runtime_client.send_request(
                target_grain=gid, grain_class=MembershipTarget,
                interface_name=MEMBERSHIP_TARGET, method_name="mbr_ping",
                args=(self.silo.silo_address,), kwargs={},
                timeout=self.probe_timeout, target_silo=target,
                category=Category.PING)
            await fut
        except Exception:  # noqa: BLE001 — timeout/rejection = missed probe
            # a miss IS a slow probe to the RTT objective — clamped UP
            # to the probe timeout, because a fast failure (connection
            # refused, immediate rejection) is at least as bad as a
            # timeout: observing its ~0 elapsed would count an outage's
            # probes as GOOD events and keep the objective green
            self.silo.stats.observe(
                _PROBE_RTT, max(time.monotonic() - t0, self.probe_timeout))
            missed = self.missed_probes.get(target, 0) + 1
            self.missed_probes[target] = missed
            self.silo.stats.increment("membership.probe.missed")
            if missed >= self.missed_limit and target in self.active:
                await self.try_suspect_or_kill(target)
        else:
            # probe round-trip latency (a few observations per second at
            # most — the QoS-category SLO source: if PING traffic ever
            # sits behind application load or batching accumulators,
            # this histogram's tail shows it BEFORE silos get voted dead)
            self.silo.stats.observe(_PROBE_RTT, time.monotonic() - t0)
            self.missed_probes[target] = 0

    # ------------------------------------------------------------------
    # Suspicion + kill (TryToSuspectOrKill, MembershipOracle.cs:949)
    # ------------------------------------------------------------------
    async def try_suspect_or_kill(self, target: SiloAddress) -> None:
        for _ in range(8):
            snap = await self.table.read_all()
            row = snap.get(target)
            if row is None:
                return
            entry, tag = row
            if entry.status == SiloStatus.DEAD:
                self.schedule_refresh()
                return
            now = time.time()
            entry = entry.copy()
            votes = entry.fresh_votes(self.vote_expiration, now)
            my_vote = self.silo.silo_address.endpoint
            if my_vote not in (v for v, _ in votes):
                votes.append((my_vote, now))
            entry.suspect_times = votes
            # enough distinct voters (capped by cluster size) → declare dead
            needed = min(self.votes_needed, max(1, len(self.active) - 1))
            if len(votes) >= needed:
                entry.status = SiloStatus.DEAD
                log.warning("%s: declaring %s dead (%d votes)",
                            self.silo.silo_address, target, len(votes))
            if await self.table.update_row(entry, tag, snap.version.next()):
                await self.refresh(gossip=True)
                return
            # CAS lost: someone else voted concurrently — retry with new etag

    # ------------------------------------------------------------------
    def _gossip_all(self) -> None:
        """One-way gossip hint to every active peer."""
        me = self.silo.silo_address
        for peer in list(self.active):
            if peer == me:
                continue
            gid = GrainId.system_target(type_code_of(MEMBERSHIP_TARGET), peer)
            try:
                self.silo.runtime_client.send_request(
                    target_grain=gid, grain_class=MembershipTarget,
                    interface_name=MEMBERSHIP_TARGET, method_name="mbr_gossip",
                    args=(me,), kwargs={}, is_one_way=True,
                    target_silo=peer, category=Category.PING)
            except Exception:  # noqa: BLE001
                pass


def join_cluster(silo: "Silo", table: MembershipTable) -> MembershipOracle:
    """Install a membership oracle on a silo (must be called before
    ``silo.start()``; the silo's start path calls ``become_active``)."""
    oracle = MembershipOracle(silo, table)
    silo.membership = oracle
    return oracle
