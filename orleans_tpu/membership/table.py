"""Cluster membership table: shared CAS store of silo liveness rows.

Re-design of /root/reference/src/Orleans.Core/SystemTargetInterfaces/
IMembershipTable.cs:14 (etag-CAS rows + monotonically versioned table) and its
backends: InMemoryMembershipTable (MembershipService/InMemoryMembershipTable.cs),
the AdoNet SQL table (src/AdoNet/Orleans.Clustering.AdoNet → sqlite here), and
a file-backed table standing in for the other external stores (Azure/ZooKeeper/
Consul — same contract, different durability substrate).

The contract (exercised uniformly by tests, mirroring
test/TesterInternal/MembershipTests/MembershipTableTestsBase.cs):
  - ``read_all`` returns every row with its etag plus the table version
  - ``insert_row``/``update_row`` are compare-and-swap on (row etag, table
    version); losers must re-read and retry
  - ``update_iam_alive`` is a non-CAS heartbeat-timestamp fast path
"""

from __future__ import annotations

import asyncio
import json
import os
import sqlite3
import time
from dataclasses import dataclass, field, replace

from ..core.ids import SiloAddress

__all__ = [
    "SiloStatus", "MembershipEntry", "TableVersion", "TableSnapshot",
    "MembershipTable", "InMemoryMembershipTable", "FileMembershipTable",
    "SqliteMembershipTable",
]


class SiloStatus:
    """Silo lifecycle states (SiloStatus enum in the reference)."""

    CREATED = "Created"
    JOINING = "Joining"
    ACTIVE = "Active"
    SHUTTING_DOWN = "ShuttingDown"
    DEAD = "Dead"


@dataclass
class MembershipEntry:
    """One silo's row (MembershipEntry in IMembershipTable.cs)."""

    address: SiloAddress
    status: str = SiloStatus.CREATED
    # suspicion votes: (voter endpoint string, unix timestamp)
    suspect_times: list[tuple[str, float]] = field(default_factory=list)
    start_time: float = 0.0
    iam_alive_time: float = 0.0

    def fresh_votes(self, expiry: float, now: float) -> list[tuple[str, float]]:
        return [(v, t) for v, t in self.suspect_times if now - t <= expiry]

    def copy(self) -> "MembershipEntry":
        return replace(self, suspect_times=list(self.suspect_times))

    # -- json round-trip (file/sqlite backends) -------------------------
    def to_json(self) -> dict:
        a = self.address
        return {
            "host": a.host, "port": a.port, "gen": a.generation,
            "mesh": a.mesh_index, "status": self.status,
            "suspects": self.suspect_times, "start": self.start_time,
            "alive": self.iam_alive_time,
        }

    @classmethod
    def from_json(cls, d: dict) -> "MembershipEntry":
        return cls(
            address=SiloAddress(d["host"], d["port"], d["gen"], d["mesh"]),
            status=d["status"],
            suspect_times=[(v, t) for v, t in d["suspects"]],
            start_time=d["start"], iam_alive_time=d["alive"],
        )


@dataclass(frozen=True)
class TableVersion:
    """Whole-table version + etag: CAS token for structural changes."""

    version: int = 0
    etag: str = "0"

    def next(self) -> "TableVersion":
        return TableVersion(self.version + 1, str(self.version + 1))


@dataclass
class TableSnapshot:
    """Result of read_all: rows with etags + the table version."""

    entries: list[tuple[MembershipEntry, str]]
    version: TableVersion

    def get(self, address: SiloAddress) -> tuple[MembershipEntry, str] | None:
        for e, tag in self.entries:
            if e.address == address:
                return e, tag
        return None


class MembershipTable:
    """Abstract CAS membership table (IMembershipTable.cs:14)."""

    async def read_all(self) -> TableSnapshot:
        raise NotImplementedError

    async def insert_row(self, entry: MembershipEntry,
                         version: TableVersion) -> bool:
        raise NotImplementedError

    async def update_row(self, entry: MembershipEntry, etag: str,
                         version: TableVersion) -> bool:
        raise NotImplementedError

    async def update_iam_alive(self, address: SiloAddress, ts: float) -> None:
        raise NotImplementedError

    async def delete_table(self) -> None:
        raise NotImplementedError


class InMemoryMembershipTable(MembershipTable):
    """Dev/test backend (InMemoryMembershipTable.cs:89): one shared object,
    atomic by virtue of the single event loop + a lock for safety."""

    def __init__(self) -> None:
        self._rows: dict[str, tuple[MembershipEntry, int]] = {}
        self._version = TableVersion()
        self._etag_counter = 0
        self._lock = asyncio.Lock()

    @staticmethod
    def _key(address: SiloAddress) -> str:
        return f"{address.endpoint}@{address.generation}"

    async def read_all(self) -> TableSnapshot:
        async with self._lock:
            return TableSnapshot(
                entries=[(e.copy(), str(tag))
                         for e, tag in self._rows.values()],
                version=self._version)

    async def insert_row(self, entry, version) -> bool:
        async with self._lock:
            if version.version != self._version.version + 1:
                return False
            key = self._key(entry.address)
            if key in self._rows:
                return False
            self._etag_counter += 1
            self._rows[key] = (entry.copy(), self._etag_counter)
            self._version = version
            return True

    async def update_row(self, entry, etag, version) -> bool:
        async with self._lock:
            if version.version != self._version.version + 1:
                return False
            key = self._key(entry.address)
            cur = self._rows.get(key)
            if cur is None or str(cur[1]) != etag:
                return False
            self._etag_counter += 1
            self._rows[key] = (entry.copy(), self._etag_counter)
            self._version = version
            return True

    async def update_iam_alive(self, address, ts) -> None:
        async with self._lock:
            cur = self._rows.get(self._key(address))
            if cur is not None:
                cur[0].iam_alive_time = ts

    async def delete_table(self) -> None:
        async with self._lock:
            self._rows.clear()
            self._version = TableVersion()


class FileMembershipTable(MembershipTable):
    """JSON-file backend: whole-file read-modify-write under an OS file lock.
    Stands in for the reference's external-store tables (Azure/ZooKeeper/
    Consul clustering packs) for single-host multi-process deployments."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = asyncio.Lock()

    def _load(self) -> tuple[dict, TableVersion]:
        if not os.path.exists(self.path):
            return {}, TableVersion()
        with open(self.path) as f:
            raw = json.load(f)
        rows = {k: (MembershipEntry.from_json(v["entry"]), v["etag"])
                for k, v in raw["rows"].items()}
        return rows, TableVersion(raw["version"], raw["etag"])

    def _store(self, rows: dict, version: TableVersion) -> None:
        raw = {
            "rows": {k: {"entry": e.to_json(), "etag": tag}
                     for k, (e, tag) in rows.items()},
            "version": version.version, "etag": version.etag,
        }
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(raw, f)
        os.replace(tmp, self.path)

    @staticmethod
    def _key(address: SiloAddress) -> str:
        return f"{address.endpoint}@{address.generation}"

    async def read_all(self) -> TableSnapshot:
        async with self._lock:
            rows, version = self._load()
            return TableSnapshot(
                entries=[(e, str(tag)) for e, tag in rows.values()],
                version=version)

    async def insert_row(self, entry, version) -> bool:
        async with self._lock:
            rows, cur = self._load()
            key = self._key(entry.address)
            if version.version != cur.version + 1 or key in rows:
                return False
            rows[key] = (entry, int(time.time_ns()))
            self._store(rows, version)
            return True

    async def update_row(self, entry, etag, version) -> bool:
        async with self._lock:
            rows, cur = self._load()
            key = self._key(entry.address)
            existing = rows.get(key)
            if (version.version != cur.version + 1 or existing is None
                    or str(existing[1]) != etag):
                return False
            rows[key] = (entry, int(time.time_ns()))
            self._store(rows, version)
            return True

    async def update_iam_alive(self, address, ts) -> None:
        async with self._lock:
            rows, version = self._load()
            cur = rows.get(self._key(address))
            if cur is not None:
                cur[0].iam_alive_time = ts
                self._store(rows, version)

    async def delete_table(self) -> None:
        async with self._lock:
            if os.path.exists(self.path):
                os.remove(self.path)


class SqliteMembershipTable(MembershipTable):
    """SQL backend over sqlite3: real conditional-UPDATE CAS, the AdoNet
    clustering analog (src/AdoNet/Orleans.Clustering.AdoNet). Safe for
    multi-process single-host clusters; ``:memory:`` works for tests."""

    def __init__(self, path: str) -> None:
        self._db = sqlite3.connect(path)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS membership ("
            " key TEXT PRIMARY KEY, entry TEXT NOT NULL, etag INTEGER)")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS version (id INTEGER PRIMARY KEY"
            " CHECK (id = 0), version INTEGER)")
        self._db.execute(
            "INSERT OR IGNORE INTO version (id, version) VALUES (0, 0)")
        self._db.commit()
        self._lock = asyncio.Lock()

    @staticmethod
    def _key(address: SiloAddress) -> str:
        return f"{address.endpoint}@{address.generation}"

    def _table_version(self) -> int:
        return self._db.execute(
            "SELECT version FROM version WHERE id=0").fetchone()[0]

    def _bump_version(self, expected_next: int) -> bool:
        cur = self._db.execute(
            "UPDATE version SET version=? WHERE id=0 AND version=?",
            (expected_next, expected_next - 1))
        return cur.rowcount == 1

    async def read_all(self) -> TableSnapshot:
        async with self._lock:
            rows = self._db.execute(
                "SELECT entry, etag FROM membership").fetchall()
            v = self._table_version()
            return TableSnapshot(
                entries=[(MembershipEntry.from_json(json.loads(e)), str(tag))
                         for e, tag in rows],
                version=TableVersion(v, str(v)))

    async def insert_row(self, entry, version) -> bool:
        async with self._lock:
            if not self._bump_version(version.version):
                self._db.rollback()
                return False
            try:
                self._db.execute(
                    "INSERT INTO membership (key, entry, etag) VALUES (?,?,1)",
                    (self._key(entry.address), json.dumps(entry.to_json())))
            except sqlite3.IntegrityError:
                self._db.rollback()
                return False
            self._db.commit()
            return True

    async def update_row(self, entry, etag, version) -> bool:
        async with self._lock:
            if not self._bump_version(version.version):
                self._db.rollback()
                return False
            cur = self._db.execute(
                "UPDATE membership SET entry=?, etag=etag+1"
                " WHERE key=? AND etag=?",
                (json.dumps(entry.to_json()), self._key(entry.address),
                 int(etag)))
            if cur.rowcount != 1:
                self._db.rollback()
                return False
            self._db.commit()
            return True

    async def update_iam_alive(self, address, ts) -> None:
        async with self._lock:
            row = self._db.execute(
                "SELECT entry FROM membership WHERE key=?",
                (self._key(address),)).fetchone()
            if row is None:
                return
            entry = MembershipEntry.from_json(json.loads(row[0]))
            entry.iam_alive_time = ts
            self._db.execute(
                "UPDATE membership SET entry=? WHERE key=?",
                (json.dumps(entry.to_json()), self._key(address)))
            self._db.commit()

    async def delete_table(self) -> None:
        async with self._lock:
            self._db.execute("DELETE FROM membership")
            self._db.execute("UPDATE version SET version=0 WHERE id=0")
            self._db.commit()
