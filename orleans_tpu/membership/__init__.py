"""Cluster membership: CAS table + probe/vote liveness oracle (reference L6,
src/Orleans.Runtime/MembershipService/)."""

from .oracle import MembershipOracle, join_cluster
from .table import (
    FileMembershipTable,
    InMemoryMembershipTable,
    MembershipEntry,
    MembershipTable,
    SiloStatus,
    SqliteMembershipTable,
    TableSnapshot,
    TableVersion,
)

__all__ = [
    "MembershipOracle", "join_cluster", "MembershipTable",
    "InMemoryMembershipTable", "FileMembershipTable", "SqliteMembershipTable",
    "MembershipEntry", "SiloStatus", "TableSnapshot", "TableVersion",
]
