"""Live metrics pipeline: sampler loop + Prometheus/OpenMetrics endpoint.

:mod:`.stats` is the passive registry — counters and histograms written
inline by the hot paths. This module turns it into a *pipeline*:

* :class:`MetricsSampler` — a periodic loop snapshotting the queue- and
  backpressure-shaped state that counters cannot express (inbound queue
  depths per QoS category, pending RPC callbacks, envelope/callback
  freelist occupancy, event-loop lag, tail-tracing buffer sizes, device
  queue depth) into :class:`WindowedGauge` series, so saturation is
  visible as a *trend* over the last window, not a point read. Each
  source also registers as a live gauge in the silo's
  :class:`~.stats.StatsRegistry` so snapshots/exposition see the current
  value. When an :class:`~.export.OtlpMetricsSink` is attached the
  sampler pushes full registry snapshots on ``otlp_period``.
* :func:`prometheus_exposition` — the registry snapshot (plus windows)
  rendered as Prometheus text exposition format 0.0.4 (counters, gauges,
  and histograms with cumulative ``le``-labelled buckets straight from
  ``Histogram.bucket_labels``/``cumulative_counts`` — no re-bucketing).
* :class:`MetricsHttpServer` — a stdlib-only (asyncio) HTTP pull
  endpoint serving ``GET /metrics`` per silo, gated on
  ``SiloConfig.metrics_port`` (``None`` disables; ``0`` binds an
  ephemeral port, readable back from ``server.port``).

The reference leans on exactly this continuous counter/queue-length
statistics surface (``src/Orleans.Core/Statistics/``, LogStatistics +
SiloRuntimeStatistics) to drive load shedding and tuning; here it is the
measurement substrate the ingest-wall work (ROADMAP #1) lands against.
"""

from __future__ import annotations

import asyncio
import logging
import re
import time
from collections import deque
from typing import TYPE_CHECKING, Callable

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.metrics")

__all__ = ["WindowedGauge", "MetricsSampler", "MetricsHttpServer",
           "prometheus_exposition"]


class WindowedGauge:
    """Time-windowed gauge series: bounded (ts, value) samples retained
    for ``window`` seconds, summarizable as last/min/max/mean — the
    "was the queue backed up in the last minute" read a point gauge
    cannot answer."""

    __slots__ = ("window", "samples")

    def __init__(self, window: float = 60.0):
        self.window = window
        self.samples: deque[tuple[float, float]] = deque()

    def add(self, value: float, ts: float | None = None) -> None:
        ts = time.monotonic() if ts is None else ts
        self.samples.append((ts, value))
        cutoff = ts - self.window
        while self.samples and self.samples[0][0] < cutoff:
            self.samples.popleft()

    def last(self) -> float:
        return self.samples[-1][1] if self.samples else 0.0

    def series(self) -> list[tuple[float, float]]:
        return list(self.samples)

    def summary(self) -> dict:
        if not self.samples:
            return {"n": 0, "last": 0.0, "min": 0.0, "max": 0.0, "mean": 0.0}
        vals = [v for _, v in self.samples]
        return {"n": len(vals), "last": vals[-1], "min": min(vals),
                "max": max(vals), "mean": sum(vals) / len(vals)}


class MetricsSampler:
    """Periodic queue/backpressure sampler for one silo.

    Sources are ``name -> callable`` pairs read on each tick; readings
    land in a :class:`WindowedGauge` per source AND register once as live
    gauges in the silo's stats registry (so ``snapshot()``, the
    Prometheus endpoint, and ``ctl_metrics`` all see current values
    without waiting for a tick). The loop also measures its own
    scheduling lag (the watchdog's signal, folded in as
    ``sampler.loop_lag`` for silos that don't install a watchdog).
    A raising source is isolated per tick — one bad gauge never starves
    the rest."""

    def __init__(self, silo: "Silo", period: float = 1.0,
                 window: float = 60.0, otlp_sink=None,
                 otlp_period: float = 5.0):
        self.silo = silo
        self.period = period
        self.window = window
        self.otlp_sink = otlp_sink
        self.otlp_period = otlp_period
        self.ticks = 0
        self._task: asyncio.Task | None = None
        self._sources: dict[str, Callable[[], float]] = {}
        self.windows: dict[str, WindowedGauge] = {}
        self._next_push = 0.0
        self._install_default_sources()

    # -- sources -----------------------------------------------------------
    def add_source(self, name: str, fn: Callable[[], float]) -> None:
        """Register a sampled series (and a live registry gauge). The
        registry-facing read is exception-hardened: a raising source must
        not break snapshot()/exposition for every other series (the same
        isolation sample_once applies tick-side)."""
        self._sources[name] = fn
        self.windows[name] = WindowedGauge(self.window)

        def read(f=fn) -> float:
            try:
                return float(f())
            except Exception:  # noqa: BLE001 — isolate a bad source
                return 0.0

        self.silo.stats.register_gauge(name, read)

    def _install_default_sources(self) -> None:
        silo = self.silo
        from ..core import message as _msg_mod
        from ..core.message import Category
        from ..runtime import runtime_client as _rc_mod

        for cat in Category:
            name = f"queue.inbound.{cat.name.lower()}"
            self.add_source(name, lambda c=cat: self._queue_depth(c))
        self.add_source("rpc.pending_callbacks",
                        lambda: len(silo.runtime_client.callbacks))
        # freelist occupancy: a draining pool under load means shells are
        # leaking (or churn outruns the cap) — envelope allocation returns
        # to the hot path exactly when it hurts most
        self.add_source("pool.message_free",
                        lambda: len(_msg_mod._MSG_POOL))
        self.add_source("pool.callback_free",
                        lambda: len(_rc_mod._CB_POOL))
        self.add_source("turns.in_flight",
                        lambda: len(silo.dispatcher._turn_tasks))
        # storage/journal queue depths (ROADMAP metrics follow-on): the
        # write-path backpressure signals — operations awaiting a storage
        # provider, and unconfirmed journaled events buffered grain-side
        self.add_source("storage.inflight_ops",
                        lambda: silo.storage_manager.inflight)
        if self._has_journaled_grains():
            # the unconfirmed-events walk is O(activations) per sample
            # tick — only worth installing when a journaled class is
            # actually registered
            self.add_source("journal.unconfirmed_events",
                            self._journal_unconfirmed)
        if silo.tracer is not None:
            self.add_source("trace.pending_traces",
                            lambda: len(silo.tracer.pending))
            self.add_source("trace.retained_spans",
                            lambda: len(silo.tracer.spans))
        if silo.message_center.egress is not None:
            # batched egress: the last response flush-group size — the
            # hand-off-unit twin of vector.staging_fill (a sustained 1
            # means responses are not grouping; the pipeline engages but
            # pays its overhead without the batching win)
            self.add_source("vector.egress_group",
                            lambda: silo.message_center.egress.last_group)
        if silo.vector is not None:
            self._install_vector_sources()
        if silo.stream_providers:
            self._install_stream_sources()
        if getattr(silo, "workers", None) is not None:
            self._install_worker_sources()

    def _install_vector_sources(self) -> None:
        silo = self.silo
        self.add_source("vector.queue_depth",
                        lambda: silo.vector.queue_depth())
        # batched-ingress staging: preallocated double-buffer footprint
        # and the last batch's fill — occupancy of the staging hand-off
        self.add_source("vector.staging_lanes",
                        lambda: silo.vector.staging_lanes())
        self.add_source("vector.staging_fill",
                        lambda: silo.vector.staging_fill)

    def _install_worker_sources(self) -> None:
        """Multi-process shm-ring health gauges, read off the owner's
        WorkerSupervisor.describe() (single-writer cumulative counters,
        so each read is torn-free):

        - ``workers.alive`` — live worker processes (a drop below
          ``worker_procs`` is the page);
        - ``workers.req_pushed/req_drained/req_backlog`` — staging-ring
          totals across workers (a growing backlog means the owner's
          drain is falling behind the workers' decode);
        - ``workers.resp_pushed/resp_drained/resp_backlog`` — the return
          leg (a growing backlog means a worker pump has stalled);
        - ``workers.route_spread`` — max-min client routes per worker
          (the accept-balance spread the multiproc floor asserts on)."""
        sup = self.silo.workers

        def _field(key: str) -> float:
            return float(sum(w.get(key, 0) or 0
                             for w in sup.describe()["workers"]))

        def _spread() -> float:
            routes = [w.get("client_routes", 0)
                      for w in sup.describe()["workers"]]
            return float(max(routes) - min(routes)) if routes else 0.0

        self.add_source("workers.alive",
                        lambda: _field("alive"))
        for key in ("req_pushed", "req_drained", "req_backlog",
                    "resp_pushed", "resp_drained", "resp_backlog"):
            self.add_source(f"workers.{key}", lambda k=key: _field(k))
        self.add_source("workers.route_spread", _spread)

    def _install_stream_sources(self) -> None:
        """Stream-provider health gauges, summed over every installed
        provider that exposes the probes (the device provider does; SMS
        and persistent providers simply contribute zero):

        - ``streams.backlog`` — cached-but-unpurged batches across all
          namespaces (rises when consumers or the pump fall behind the
          publishers);
        - ``streams.cursor_lag`` — worst cursor distance from the write
          head in batches (a stuck rewound consumer shows here long
          before the backlog gauge moves, because its cursor pins the
          purge floor);
        - ``streams.delivery_group`` — rows in the last compiled delivery
          batch (edges x items): the hand-off-unit twin of
          ``vector.egress_group`` — a sustained 1 means fan-out is not
          batching and the device path pays its overhead for nothing."""
        providers = self.silo.stream_providers

        def _sum(probe: str) -> float:
            total = 0.0
            for p in providers.values():
                fn = getattr(p, probe, None)
                if fn is not None:
                    total += float(fn())
            return total

        self.add_source("streams.backlog",
                        lambda: _sum("stream_backlog"))
        self.add_source("streams.cursor_lag",
                        lambda: _sum("stream_cursor_lag"))
        self.add_source("streams.delivery_group",
                        lambda: _sum("stream_delivery_group"))

    def _has_journaled_grains(self) -> bool:
        from ..eventsourcing.journaled import JournaledGrain
        return any(isinstance(c, type) and issubclass(c, JournaledGrain)
                   for c in self.silo.registry.all_classes())

    def _journal_unconfirmed(self) -> float:
        """Unconfirmed (tentative) journaled events across every local
        activation — >0 sustained means confirm_events is outrunning the
        journal provider. Scoped to real JournaledGrain instances: an
        application grain's private ``_pending`` attribute must not
        inflate the gauge."""
        from ..eventsourcing.journaled import JournaledGrain
        total = 0
        for act in self.silo.catalog.by_activation.values():
            inst = act.grain_instance
            if isinstance(inst, JournaledGrain):
                # default for an instance still mid-activation
                total += len(getattr(inst, "_pending", ()))
        return float(total)

    def _queue_depth(self, cat) -> float:
        q = self.silo.message_center.inbound.get(cat)
        return float(q.qsize()) if q is not None else 0.0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self.silo.vector is not None and \
                "vector.queue_depth" not in self._sources:
            # the device tier may have been installed after construction
            self._install_vector_sources()
        if self.silo.stream_providers and \
                "streams.backlog" not in self._sources:
            # stream providers install via lifecycle stages that run
            # after the sampler is constructed
            self._install_stream_sources()
        if getattr(self.silo, "workers", None) is not None and \
                "workers.alive" not in self._sources:
            # the worker supervisor spawns during silo start, after the
            # sampler is constructed
            self._install_worker_sources()
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        from .profiling import mark_loop_category
        mark_loop_category("observability")  # this task's steps are ours
        loop_lag = WindowedGauge(self.window)
        self.windows["sampler.loop_lag"] = loop_lag
        self.silo.stats.register_gauge("sampler.loop_lag", loop_lag.last)
        lag_threshold = getattr(self.silo.config,
                                "profiling_lag_threshold", 0.25)
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(self.period)
            now = time.monotonic()
            lag = max(0.0, (now - t0) - self.period)
            loop_lag.add(lag, now)
            lp = self.silo.loop_prof
            if lp is not None and lag > lag_threshold:
                # the loop is visibly stalling: snapshot the flight
                # recorder (covers silos that run no Watchdog; the
                # watchdog has its own trigger at its lag_warning)
                lp.trigger("sampler_lag", lag=round(lag, 4))
            self.sample_once(now)
            if self.otlp_sink is not None and now >= self._next_push:
                self._next_push = now + self.otlp_period
                self.push_snapshot()

    def sample_once(self, ts: float | None = None) -> None:
        """One sampling pass (the loop body; callable directly in tests)."""
        ts = time.monotonic() if ts is None else ts
        self.ticks += 1
        for name, fn in self._sources.items():
            try:
                self.windows[name].add(float(fn()), ts)
            except Exception:  # noqa: BLE001 — isolate a bad source
                log.exception("metrics source %s failed", name)

    def push_snapshot(self) -> None:
        """Offer one full registry snapshot to the OTLP metrics sink."""
        if self.otlp_sink is None:
            return
        snap = self.silo.stats.snapshot()
        snap["silo"] = self.silo.config.name
        self.otlp_sink.offer((snap,))

    def window_snapshot(self) -> dict:
        """Per-source window summaries (management surface payload)."""
        return {name: w.summary() for name, w in self.windows.items()}


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str) -> str:
    return f"{prefix}_{_NAME_RE.sub('_', name)}"


def _fmt(v: float) -> str:
    return repr(float(v)) if isinstance(v, float) and not v.is_integer() \
        else str(int(v))


def prometheus_exposition(snapshot: dict, windows: dict | None = None,
                          prefix: str = "orleans",
                          labels: dict | None = None,
                          openmetrics: bool = False) -> str:
    """Render a ``StatsRegistry.snapshot()`` (plus optional sampler
    window summaries) as Prometheus text exposition format 0.0.4, or —
    with ``openmetrics`` — as OpenMetrics 1.0 text (``_total`` counter
    samples, ``# EOF`` terminator, and histogram-bucket exemplars).

    Histograms serve their native fixed buckets — cumulative counts with
    ``le`` labels from :meth:`Histogram.bucket_labels` — plus ``_sum``
    and ``_count``; window summaries become ``_min``/``_max``/``_avg``
    gauge triples beside the live gauge.  Exemplars (the sampled trace
    id riding a slow bucket) are only legal in the OpenMetrics format —
    the classic 0.0.4 rendering omits them so strict parsers never see
    tokens after the sample value."""
    lbl = ""
    if labels:
        def esc(v) -> str:
            return str(v).replace("\\", "\\\\").replace('"', '\\"')
        inner = ",".join(f'{k}="{esc(v)}"' for k, v in labels.items())
        lbl = "{" + inner + "}"
    lines: list[str] = []
    for name, v in sorted(snapshot.get("counters", {}).items()):
        n = _prom_name(name, prefix)
        lines.append(f"# TYPE {n} counter")
        # OpenMetrics requires counter samples to carry the _total suffix
        lines.append(f"{n}{'_total' if openmetrics else ''}{lbl} {_fmt(v)}")
    for name, v in sorted(snapshot.get("gauges", {}).items()):
        n = _prom_name(name, prefix)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n}{lbl} {_fmt(v)}")
    from .stats import Histogram
    for name, h in sorted(snapshot.get("histograms", {}).items()):
        n = _prom_name(name, prefix)
        hist = Histogram.from_snapshot(h)
        lines.append(f"# TYPE {n} histogram")
        exemplars = hist.exemplars or {}
        for i, (le, cum) in enumerate(zip(hist.bucket_labels(),
                                          hist.cumulative_counts())):
            if lbl:
                blbl = lbl[:-1] + f',le="{le}"}}'
            else:
                blbl = f'{{le="{le}"}}'
            line = f"{n}_bucket{blbl} {cum}"
            ex = exemplars.get(i) if openmetrics else None
            if ex is not None:
                # OpenMetrics exemplar syntax: the sampled trace id on the
                # bucket its observation landed in — a slow bucket links
                # straight into the tail-retained trace that filled it.
                # Same 32-hex width as the OTLP span export so backends
                # joining exemplar -> trace by exact id string match.
                v, tid, ts = ex
                line += (f' # {{trace_id="{int(tid):032x}"}} '
                         f'{float(v):.6g} {float(ts):.3f}')
            lines.append(line)
        lines.append(f"{n}_sum{lbl} {repr(float(hist.sum))}")
        lines.append(f"{n}_count{lbl} {hist.total}")
    for name, w in sorted((windows or {}).items()):
        n = _prom_name(name, prefix)
        for suffix, key in (("_window_min", "min"), ("_window_max", "max"),
                            ("_window_avg", "mean")):
            lines.append(f"# TYPE {n}{suffix} gauge")
            lines.append(f"{n}{suffix}{lbl} {repr(float(w.get(key, 0.0)))}")
    if openmetrics:
        lines.append("# EOF")
    return "\n".join(lines) + "\n"


class MetricsHttpServer:
    """Minimal asyncio HTTP server answering ``GET /metrics`` with the
    silo's exposition (stdlib-only; one server per silo, gated on
    ``SiloConfig.metrics_port``). Port 0 binds ephemeral — the bound
    port is readable from ``.port`` after :meth:`start`."""

    def __init__(self, silo: "Silo", host: str = "127.0.0.1"):
        self.silo = silo
        self.host = host
        self.port: int | None = None
        self._server: asyncio.base_events.Server | None = None

    async def start(self, port: int = 0) -> "MetricsHttpServer":
        self._server = await asyncio.start_server(
            self._handle, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("metrics endpoint for %s on http://%s:%d/metrics",
                 self.silo.config.name, self.host, self.port)
        return self

    def render(self, openmetrics: bool = False) -> str:
        windows = None
        sampler = self.silo.metrics
        if sampler is not None:
            windows = sampler.window_snapshot()
        return prometheus_exposition(
            self.silo.stats.snapshot(), windows,
            labels={"silo": self.silo.config.name},
            openmetrics=openmetrics)

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request_line = await reader.readline()
            # drain headers to the blank line, watching for the scraper
            # negotiating OpenMetrics (exemplars are only legal there)
            openmetrics = False
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                if line[:7].lower() == b"accept:" and \
                        b"application/openmetrics-text" in line:
                    openmetrics = True
            parts = request_line.split()
            path = parts[1].decode("latin-1") if len(parts) >= 2 else "/"
            if len(parts) >= 1 and parts[0] == b"GET" and \
                    path.split("?", 1)[0] in ("/metrics", "/"):
                body = self.render(openmetrics).encode()
                ctype = (b"application/openmetrics-text; version=1.0.0; "
                         b"charset=utf-8" if openmetrics else
                         b"text/plain; version=0.0.4; charset=utf-8")
                head = (b"HTTP/1.1 200 OK\r\n"
                        b"Content-Type: " + ctype + b"\r\n"
                        b"Content-Length: " + str(len(body)).encode() +
                        b"\r\nConnection: close\r\n\r\n")
                writer.write(head + body)
            else:
                writer.write(b"HTTP/1.1 404 Not Found\r\n"
                             b"Content-Length: 0\r\n"
                             b"Connection: close\r\n\r\n")
            await writer.drain()
        except (ConnectionResetError, asyncio.IncompleteReadError, OSError):
            pass  # scraper went away mid-request
        except Exception:  # noqa: BLE001 — a bad request must not log-spam
            log.exception("metrics request handling failed")
        finally:
            writer.close()

    async def aclose(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
