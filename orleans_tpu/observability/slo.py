"""SLO engine: declarative objectives + multi-window burn-rate alerting.

The observability substrate (stage histograms, sampler gauges, tail
tracing, the loop profiler + flight recorder) *records*; this module
*judges*. A :class:`SloSpec` declares an objective — a latency-percentile
target over a registry histogram (route / QoS-category keyed), an
error-rate target, or a shed-rate target — with an error budget
(``1 - target``). A per-silo :class:`SloMonitor` loop evaluates every
spec from **interval-diffed snapshots** of the existing
``INGEST_STATS``/``EGRESS_STATS`` histograms and counters
(:meth:`Histogram.delta` is the primitive): zero new hot-path
instrumentation — the hot path keeps paying exactly the stamps it
already pays for metrics, and evaluation rides the observability
category of the loop at ``slo_period`` cadence.

Detection is Google-SRE-style **multi-window burn rate**: each interval's
(good, bad) event counts land in a bounded series; the *fast* window
catches spikes (a flash crowd torches the budget within seconds), the
*slow* window confirms sustained burn (a single GC pause or noisy
interval does not page). An objective breaches when BOTH windows burn
faster than ``burn_threshold`` × the budget rate with at least
``min_events`` events in the fast window; it recovers when the fast
window cools below the threshold.

The breach path is wired end-to-end:

* the PR-8 **flight recorder** snapshots the loop-occupancy ring
  (trigger reason ``slo_breach``, rate-limited, carrying the breached
  objective + burn rates) — the "what occupied the loop while the SLO
  died" evidence;
* in-flight **tail traces** are force-retained
  (:meth:`SpanCollector.force_retain` over the pending map), so the
  requests that were in the air during the breach survive the tail
  keep/drop decision and export with the breach;
* ``slo.*`` **gauges/counters** land in the stats registry (Prometheus /
  OTLP / ``ctl_metrics`` see them like any other series) and a
  ``slo_breach`` **telemetry event** fans out to the consumers;
* the cluster rolls up via ``SiloControl.ctl_slo`` →
  ``ManagementGrain.get_cluster_slo`` (worst-burn-wins merge with
  per-silo drill-down, including each silo's hottest call sites from the
  :class:`~.stats.CallSiteStats` table).

Hard QoS constraint, preserved by construction: PING/SYSTEM responses
never sit behind SLO evaluation — the monitor reads a handful of named
registry series per tick (never a full registry snapshot), runs in the
``observability`` loop category, and the default spec set *asserts* the
probe path (``membership.probe.rtt.seconds``) as its own objective.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from .stats import SLO_STATS, Histogram

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.slo")

__all__ = ["SloSpec", "SloMonitor", "default_specs"]


@dataclass
class SloSpec:
    """One declarative objective.

    ``kind``:

    * ``"latency"`` — good events are observations of the ``source``
      histogram at or under ``threshold`` seconds (bucket-conservative:
      the bucket the threshold falls inside counts as bad);
    * ``"error_rate"`` — bad events are the ``bad_source`` counter's
      interval delta, total events the ``total_source`` counter's;
    * ``"shed_rate"`` — bad events are the ``bad_source`` (gateway shed)
      delta, total events bad + ``total_source`` (accepted ingress).

    ``target`` is the good fraction the objective promises (0.99 = 99%
    of events good); the error budget is ``1 - target``. Burn rate is
    the observed bad fraction over the budget — burn 1.0 spends the
    budget exactly at the promised rate, burn N spends it N× too fast.
    A breach requires BOTH windows over ``burn_threshold`` (fast catches,
    slow confirms) and ``min_events`` events in the fast window."""

    name: str
    kind: str = "latency"
    target: float = 0.99
    threshold: float = 0.1            # latency kinds: good <= this (s)
    source: str | None = None         # latency: registry histogram name
    bad_source: str | None = None     # ratio kinds: bad-event counter
    # ratio kinds: total-event counter name, or a tuple of names summed
    # (e.g. host turns + device-tier messages)
    total_source: "str | tuple[str, ...] | None" = None
    fast_window: float = 60.0
    slow_window: float = 300.0
    burn_threshold: float = 4.0
    min_events: int = 10
    # free-form labels (route/class.method/QoS category) for dashboards
    labels: dict = field(default_factory=dict)

    def validate(self) -> None:
        from ..core.errors import ConfigurationError
        if self.kind not in ("latency", "error_rate", "shed_rate"):
            raise ConfigurationError(
                f"SloSpec {self.name!r}: unknown kind {self.kind!r}")
        if not (0.0 < self.target < 1.0):
            raise ConfigurationError(
                f"SloSpec {self.name!r}: target must be in (0, 1), got "
                f"{self.target!r} — target 1.0 has zero error budget and "
                "every bad event is an infinite burn")
        if self.fast_window >= self.slow_window:
            raise ConfigurationError(
                f"SloSpec {self.name!r}: fast_window must be < "
                f"slow_window ({self.fast_window} >= {self.slow_window})")
        if self.kind == "latency" and not self.source:
            raise ConfigurationError(
                f"SloSpec {self.name!r}: latency objectives need a "
                "source histogram name")

    @property
    def budget(self) -> float:
        return 1.0 - self.target


def default_specs(config) -> list[SloSpec]:
    """The standard per-silo objective set, parameterized by the
    ``slo_*`` config knobs:

    * ``app_latency`` — ingest queue-wait (the per-message backpressure
      face every delivered message observes when metrics are on);
    * ``probe_rtt`` — membership probe round trips under the probe
      timeout (the QoS invariant as an OBJECTIVE: if PING traffic ever
      sits behind application load, this burns first);
    * ``turn_errors`` — host-turn failures over delivered application
      messages;
    * ``shed_rate`` — gateway sheds over offered client ingress.

    With metrics DISABLED only the probe-RTT objective is installed:
    the latency histogram and the turn/message totals are metrics-gated,
    and a ratio objective whose BAD counters (turn errors, gateway
    sheds) still increment against a gated-off total would read every
    bad event as a 100%-bad interval and fabricate a breach. Objectives
    whose sources simply never observe (no membership, shedding
    disabled) report zero events and never burn."""
    fw, sw = config.slo_fast_window, config.slo_slow_window
    bt, me = config.slo_burn_threshold, config.slo_min_events
    common = dict(fast_window=fw, slow_window=sw, burn_threshold=bt,
                  min_events=me)
    probe = SloSpec("probe_rtt", kind="latency",
                    target=config.slo_probe_target,
                    threshold=config.membership_probe_timeout,
                    source=SLO_STATS["probe_rtt"],
                    labels={"route": "membership.probe", "qos": "PING"},
                    **common)
    if not config.metrics_enabled:
        return [probe]
    return [
        SloSpec("app_latency", kind="latency",
                target=config.slo_latency_target,
                threshold=config.slo_latency_threshold,
                source="ingest.queue_wait.seconds",
                labels={"route": "ingest", "qos": "APPLICATION"},
                **common),
        probe,
        # turn-denominated totals: ``messaging.received.application``
        # would count inbound RESPONSES and forwarded legs too (2-3x the
        # real event count across a cluster), silently diluting burn —
        # ``ingest.turns`` + ``ingest.messages`` are observed at the
        # same owning-silo sites the bad events come from
        SloSpec("turn_errors", kind="error_rate",
                target=config.slo_error_target,
                bad_source=SLO_STATS["turn_errors"],
                total_source="ingest.turns",
                labels={"route": "turns"}, **common),
        SloSpec("shed_rate", kind="shed_rate",
                target=config.slo_shed_target,
                bad_source="messaging.gateway.shed",
                total_source=("ingest.turns", "ingest.messages"),
                labels={"route": "gateway"}, **common),
        # device-tier stream delivery: publish -> consumer-turn hand-off
        # (streams.delivery.seconds is observed by the device provider's
        # pump when the compiled fan-out round lands). Zero observations
        # when no stream provider is installed -> never burns.
        SloSpec("stream_latency", kind="latency",
                target=config.slo_stream_target,
                threshold=config.slo_stream_threshold,
                source="streams.delivery.seconds",
                labels={"route": "streams.device", "qos": "APPLICATION"},
                **common),
    ]


class _Series:
    """Bounded (ts, good, bad) interval samples with windowed sums —
    the burn-rate windows' substrate. Samples older than the slow
    window evict on every add; windowed reads walk the (small: one
    entry per monitor tick) deque."""

    __slots__ = ("max_age", "samples")

    def __init__(self, max_age: float):
        self.max_age = max_age
        self.samples: deque[tuple[float, int, int]] = deque()

    def add(self, ts: float, good: int, bad: int) -> None:
        self.samples.append((ts, good, bad))
        cutoff = ts - self.max_age
        while self.samples and self.samples[0][0] < cutoff:
            self.samples.popleft()

    def window(self, now: float, w: float) -> tuple[int, int]:
        cutoff = now - w
        good = bad = 0
        for ts, g, b in reversed(self.samples):
            if ts < cutoff:
                break
            good += g
            bad += b
        return good, bad


class _Objective:
    """Evaluation state for one spec: the interval series, the previous
    cumulative reads (histogram summary / counter values) the next
    interval diffs against, cumulative good/bad for budget accounting,
    and the breach episode state."""

    __slots__ = ("spec", "series", "prev_hist", "prev_counters",
                 "cum_good", "cum_bad", "breached", "breaches",
                 "breach_started_mono", "breach_started_wall",
                 "first_breach_mono", "episodes", "burn_fast",
                 "burn_slow", "_first_ts")

    def __init__(self, spec: SloSpec):
        self.spec = spec
        self.series = _Series(spec.slow_window)
        self.prev_hist: dict | None = None
        self.prev_counters: dict[str, int] = {}
        self.cum_good = 0
        self.cum_bad = 0
        self.breached = False
        self.breaches = 0
        self.breach_started_mono: float | None = None
        self.breach_started_wall: float | None = None
        self.first_breach_mono: float | None = None
        # monotonic start of each breach episode (bounded): harnesses
        # measure time-to-detect against the first episode AT/AFTER
        # their overload onset, not a stale warmup episode
        self.episodes: list[float] = []
        self.burn_fast = 0.0
        self.burn_slow = 0.0
        self._first_ts: float | None = None  # first evaluation tick

    # -- interval collection ------------------------------------------------
    def collect(self, stats) -> tuple[int, int]:
        """(good, bad) events since the previous tick, from the registry
        — cumulative state diffs here, never on the hot path."""
        spec = self.spec
        if spec.kind == "latency":
            h = stats.histograms.get(spec.source)
            if h is None:
                return 0, 0
            d = h.delta(self.prev_hist)
            self.prev_hist = h.summary()
            good = d.good_below(spec.threshold)
            return good, d.total - good
        bad = self._counter_delta(stats, spec.bad_source)
        total = self._counter_delta(stats, spec.total_source)
        if spec.kind == "shed_rate":
            # shed messages never execute: offered = executed + shed
            return total, bad
        return max(0, total - bad), bad

    def _counter_delta(self, stats, name) -> int:
        if not name:
            return 0
        if isinstance(name, tuple):
            return sum(self._counter_delta(stats, n) for n in name)
        cur = stats.counters.get(name, 0)
        prev = self.prev_counters.get(name, 0)
        self.prev_counters[name] = cur
        return max(0, cur - prev)

    # -- burn math -----------------------------------------------------------
    @staticmethod
    def _burn(good: int, bad: int, budget: float) -> float:
        total = good + bad
        if total <= 0:
            return 0.0
        return (bad / total) / budget

    def evaluate(self, now: float, good: int, bad: int) -> bool:
        """Fold one interval in; returns True on a NEW breach episode."""
        spec = self.spec
        if self._first_ts is None:
            self._first_ts = now
        self.series.add(now, good, bad)
        self.cum_good += good
        self.cum_bad += bad
        fg, fb = self.series.window(now, spec.fast_window)
        sg, sb = self.series.window(now, spec.slow_window)
        self.burn_fast = self._burn(fg, fb, spec.budget)
        self.burn_slow = self._burn(sg, sb, spec.budget)
        if not self.breached:
            if (fg + fb >= spec.min_events
                    # cold-start guard: until the series has SPANNED a
                    # full slow window, the slow window holds the same
                    # samples as the fast one and would rubber-stamp a
                    # single bad interval — the multi-window confirm
                    # only means something once the windows differ
                    and now - self._first_ts >= spec.slow_window
                    and self.burn_fast >= spec.burn_threshold
                    and self.burn_slow >= spec.burn_threshold):
                self.breached = True
                self.breaches += 1
                self.breach_started_mono = now
                self.breach_started_wall = time.time()
                if self.first_breach_mono is None:
                    self.first_breach_mono = now
                if len(self.episodes) < 64:
                    self.episodes.append(now)
                return True
        elif self.burn_fast < spec.burn_threshold:
            # recovery: the fast window cooled below the alert rate
            # (the slow window may still carry the episode's debris)
            self.breached = False
        return False

    @property
    def budget_burned(self) -> float:
        """Fraction of the cumulative error budget consumed since the
        monitor started (>1 = over budget for the observed volume)."""
        total = self.cum_good + self.cum_bad
        if total <= 0:
            return 0.0
        return (self.cum_bad / total) / self.spec.budget

    def status(self) -> dict:
        spec = self.spec
        out = {
            "kind": spec.kind,
            "target": spec.target,
            "burn_threshold": spec.burn_threshold,
            "fast_window": spec.fast_window,
            "slow_window": spec.slow_window,
            "met": not self.breached,
            "breached": self.breached,
            "breaches": self.breaches,
            "burn_fast": round(self.burn_fast, 4),
            "burn_slow": round(self.burn_slow, 4),
            "budget_burned": round(self.budget_burned, 4),
            "good": self.cum_good,
            "bad": self.cum_bad,
            "labels": dict(spec.labels),
        }
        if spec.kind == "latency":
            out["threshold"] = spec.threshold
            out["source"] = spec.source
        if self.breach_started_wall is not None:
            out["breach_started"] = self.breach_started_wall
            out["breach_started_mono"] = self.breach_started_mono
        if self.first_breach_mono is not None:
            out["first_breach_mono"] = self.first_breach_mono
            out["episodes"] = list(self.episodes)
        return out


class SloMonitor:
    """Per-silo SLO evaluation loop.

    Construction never touches the hot path: every tick reads the named
    registry series each objective declares (a few dict gets + one
    :meth:`Histogram.delta` per latency objective), folds the interval
    into the burn windows, refreshes the ``slo.*`` gauges, and — on a
    breach transition — fires the breach path (flight recorder,
    tail-trace force-retention, telemetry). ``evaluate_once`` is callable
    directly with an injected clock for deterministic tests and for
    harnesses that want a final read before teardown."""

    def __init__(self, silo: "Silo", specs: list[SloSpec] | None = None,
                 period: float | None = None):
        self.silo = silo
        self.period = (period if period is not None
                       else silo.config.slo_period)
        specs = list(specs) if specs else default_specs(silo.config)
        for s in specs:
            s.validate()
        self.objectives = {s.name: _Objective(s) for s in specs}
        self.ticks = 0
        self._task: asyncio.Task | None = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        from .profiling import mark_loop_category
        mark_loop_category("observability")  # evaluation is OUR tax,
        # never booked to turns/pump — and never in front of them: each
        # tick is one short callback run between loop turns
        while True:
            await asyncio.sleep(self.period)
            try:
                self.evaluate_once()
            except Exception:  # noqa: BLE001 — a bad source must not
                log.exception("SLO evaluation failed")  # kill the loop

    # -- evaluation --------------------------------------------------------
    def evaluate_once(self, now: float | None = None) -> list[str]:
        """One evaluation pass; returns the objectives that entered a
        NEW breach episode this tick (tests and harnesses read it)."""
        now = time.monotonic() if now is None else now
        stats = self.silo.stats
        self.ticks += 1
        stats.increment(SLO_STATS["evaluations"])
        newly_breached: list[str] = []
        for name, obj in self.objectives.items():
            good, bad = obj.collect(stats)
            if obj.evaluate(now, good, bad):
                newly_breached.append(name)
            stats.set_gauge(SLO_STATS["burn_fast"] % name, obj.burn_fast)
            stats.set_gauge(SLO_STATS["burn_slow"] % name, obj.burn_slow)
            stats.set_gauge(SLO_STATS["budget_burned"] % name,
                            obj.budget_burned)
            stats.set_gauge(SLO_STATS["breached"] % name,
                            1.0 if obj.breached else 0.0)
        for name in newly_breached:
            self._on_breach(self.objectives[name])
        return newly_breached

    def _on_breach(self, obj: _Objective) -> None:
        """The wired breach path: counters, flight recorder, tail-trace
        force-retention, telemetry. Every step is isolated — observing a
        breach must never make the overload worse."""
        silo = self.silo
        name = obj.spec.name
        silo.stats.increment(SLO_STATS["breaches"])
        silo.stats.increment(SLO_STATS["breach"] % name)
        # WHO was burning when the breach fired: the cost ledger's top
        # keys, tenant-annotated — attached to the flight snapshot and
        # the telemetry event so the drill-down starts named
        burners: list = []
        led = getattr(silo, "ledger", None)   # unit fakes omit the attr
        if led is not None:
            try:
                burners = led.top_burners(5)
            except Exception:  # noqa: BLE001
                log.exception("slo breach ledger read failed")
        log.warning("SLO breach on %s: %s burn fast=%.1fx slow=%.1fx "
                    "(threshold %.1fx, target %s)", silo.config.name, name,
                    obj.burn_fast, obj.burn_slow, obj.spec.burn_threshold,
                    obj.spec.target)
        lp = silo.loop_prof
        if lp is not None:
            # flight recorder: the loop-occupancy ring around the breach
            # IS the first diagnostic — snapshot it (rate-limited per
            # reason inside trigger) carrying the breached objective
            try:
                lp.trigger("slo_breach", objective=name,
                           burn_fast=round(obj.burn_fast, 2),
                           burn_slow=round(obj.burn_slow, 2),
                           target=obj.spec.target,
                           top_burners=burners)
            except Exception:  # noqa: BLE001
                log.exception("slo breach flight trigger failed")
        tracer = silo.tracer
        if tracer is not None and tracer.tail:
            # in-flight tail traces: whatever is pending RIGHT NOW was in
            # the air during the breach — pin it through the keep/drop
            # decision so the breach exports with its requests
            try:
                for tid in list(tracer.pending):
                    tracer.force_retain(tid)
            except Exception:  # noqa: BLE001
                log.exception("slo breach trace force-retention failed")
        tm = getattr(silo, "telemetry", None)
        if tm is not None:
            try:
                tm.track_event("slo_breach", objective=name,
                               burn_fast=round(obj.burn_fast, 2),
                               burn_slow=round(obj.burn_slow, 2),
                               budget_burned=round(obj.budget_burned, 4),
                               silo=silo.config.name,
                               top_burners=burners)
            except Exception:  # noqa: BLE001
                log.exception("slo breach telemetry failed")

    # -- reads -------------------------------------------------------------
    @property
    def breached(self) -> bool:
        return any(o.breached for o in self.objectives.values())

    def status(self) -> dict:
        """The management-surface payload (``ctl_slo``): every
        objective's verdict + burn state, plus the monitor's own
        cadence evidence."""
        return {
            "silo": self.silo.config.name,
            "period": self.period,
            "ticks": self.ticks,
            "breached": self.breached,
            "breaches": sum(o.breaches for o in self.objectives.values()),
            "objectives": {n: o.status()
                           for n, o in self.objectives.items()},
        }
