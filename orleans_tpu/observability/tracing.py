"""End-to-end distributed request tracing (L13).

The reference's tracing story is ActivityId correlation riding message
headers plus hot-path counters (SURVEY §5 "Tracing / profiling" —
RequestContext carries the ActivityId; Message.DebugContext stamps hops).
This module grows that into a W3C-style trace/span model:

* a **trace context** ``(trace_id, parent_span_id, sent_at)`` rides the
  existing ``RequestContext`` message headers under :data:`TRACE_KEY`, so
  one logical request keeps one ``trace_id`` across silo hops, forwarded
  (post-migration) hops, directory RPCs, and device-tier ticks;
* spans are opened automatically at the call sites the runtime owns —
  client invoke (``runtime_client``), server turn with queue-wait vs.
  execution split (``runtime/dispatcher``), the network leg (stamped
  send-side, measured receive-side), directory lookups
  (``directory/locator``), device ticks (``dispatch/engine``, bridged to
  ``jax.profiler.TraceAnnotation`` so XLA kernels nest under the logical
  span), and rebalance migration legs (``rebalance/executor``);
* a per-silo :class:`SpanCollector` ring buffer holds finished spans with
  a head-based sampling knob (``config.TracingOptions`` /
  ``trace_sample_rate``): the ROOT of a trace rolls the sampling die once
  and unsampled requests carry no header and record nothing downstream —
  at ``sample_rate=0`` the hot path pays one attribute check per call
  (guarded by ``tests/test_perf_floors.py::test_floor_trace_overhead``).

Consumers: the management surface (``SiloControl.ctl_trace_spans`` +
``ManagementGrain.get_trace_breakdown``) for cluster-wide critical-path
queries, and :mod:`orleans_tpu.observability.export` for Chrome-trace/
Perfetto timeline files merging every silo of a cluster.

Span ``start`` times are wall-clock (``time.time()``) so spans from
different silos/processes merge onto one timeline; durations are measured
with the monotonic clock.
"""

from __future__ import annotations

import contextvars
import random
import time
from collections import deque

__all__ = [
    "TRACE_KEY", "Span", "SpanCollector", "current_trace",
    "new_trace_id", "new_span_id", "critical_path_breakdown",
]

# RequestContext/message-header key the trace context rides under (the
# ActivityId header analog): (trace_id, parent_span_id, sent_at_wall).
# Present if and only if the trace is sampled — head-based sampling.
TRACE_KEY = "orleans.trace"

# The span context ambient to the running turn/callsite: (trace_id,
# span_id) of the span any nested outgoing call should parent under.
# None outside sampled traces (the common case — one ContextVar.get on
# the send path is the whole cost of disabled tracing there).
current_trace: contextvars.ContextVar[tuple[int, int] | None] = (
    contextvars.ContextVar("orleans_current_trace", default=None)
)

# span kinds a collector records; critical_path_breakdown buckets by these
SPAN_KINDS = ("client", "server", "network", "directory", "device",
              "device_tick", "migration")


def new_trace_id() -> int:
    """63-bit random id (unique across silos without coordination)."""
    return random.getrandbits(63) or 1


def new_span_id() -> int:
    return random.getrandbits(63) or 1


class Span:
    """One timed operation. ``start`` is wall-clock seconds; ``duration``
    is a monotonic-clock delta (set by :meth:`SpanCollector.close`)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "kind",
                 "silo", "start", "duration", "attrs", "_t0")

    def __init__(self, trace_id: int, span_id: int, parent_id: int | None,
                 name: str, kind: str, silo: str, start: float,
                 duration: float = 0.0, attrs: dict | None = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.silo = silo
        self.start = start
        self.duration = duration
        self.attrs = attrs
        self._t0 = 0.0

    def to_dict(self) -> dict:
        """Wire/JSON form (what ``ctl_trace_spans`` and the exporter see)."""
        return {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "kind": self.kind, "silo": self.silo, "start": self.start,
            "duration": self.duration, "attrs": self.attrs or {},
        }

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return (f"<Span {self.kind} {self.name!r} {self.duration * 1e3:.3f}ms"
                f" trace={self.trace_id:x}>")


class SpanCollector:
    """Per-silo (or per-client) span sink: bounded ring buffer + the
    head-based sampling decision. Cheap enough for the hot path — an
    unsampled call never reaches it, and a sampled span costs two clock
    reads, one random id, and a deque append."""

    def __init__(self, name: str, sample_rate: float = 1.0,
                 buffer_size: int = 4096):
        self.name = name
        self.sample_rate = sample_rate
        self.spans: deque[Span] = deque(maxlen=buffer_size)
        # synthetic trace grouping device ticks not tied to one request
        self.device_trace_id = new_trace_id()

    # -- sampling (root decision; propagated via header presence) --------
    def sample(self) -> bool:
        r = self.sample_rate
        if r >= 1.0:
            return True
        if r <= 0.0:
            return False
        return random.random() < r

    def new_trace_id(self) -> int:
        return new_trace_id()

    # -- span lifecycle ---------------------------------------------------
    def open(self, name: str, kind: str, trace_id: int,
             parent_id: int | None) -> Span:
        span = Span(trace_id, new_span_id(), parent_id, name, kind,
                    self.name, time.time())
        span._t0 = time.monotonic()
        return span

    def close(self, span: Span, duration: float | None = None,
              **attrs) -> Span:
        span.duration = (time.monotonic() - span._t0
                         if duration is None else duration)
        if attrs:
            span.attrs = attrs
        self.spans.append(span)
        return span

    def record(self, trace_id: int, parent_id: int | None, name: str,
               kind: str, start: float, duration: float, **attrs) -> Span:
        """Record a span whose timing was measured externally (e.g. the
        network leg: stamped send-side, observed receive-side)."""
        span = Span(trace_id, new_span_id(), parent_id, name, kind,
                    self.name, start, max(0.0, duration), attrs or None)
        self.spans.append(span)
        return span

    # -- reads -------------------------------------------------------------
    def snapshot(self, trace_id: int | None = None,
                 limit: int | None = None) -> list[dict]:
        spans = list(self.spans)
        if trace_id is not None:
            spans = [s for s in spans if s.trace_id == trace_id]
        if limit is not None and len(spans) > limit:
            spans = spans[-limit:]
        return [s.to_dict() for s in spans]

    def trace_ids(self) -> list[int]:
        seen: dict[int, None] = {}
        for s in self.spans:
            seen.setdefault(s.trace_id, None)
        return list(seen)

    def clear(self) -> None:
        self.spans.clear()


def context_from_headers(request_context: dict | None
                         ) -> tuple[int, int, float] | None:
    """Extract ``(trace_id, parent_span_id, sent_at)`` from message
    baggage; None when the request is untraced/unsampled OR the header is
    malformed. RequestContext is app-writable, so every runtime consumer
    parses through this single hardened path — garbage baggage must never
    break a turn or drop a message, it just goes untraced."""
    if not request_context:
        return None
    hdr = request_context.get(TRACE_KEY)
    if hdr is None:
        return None
    try:
        # tolerate list-decoded tuples from portable codecs
        t, p, s = hdr
        return (int(t), int(p), float(s))
    except (TypeError, ValueError):
        return None


def restamp_header(request_context: dict | None) -> dict | None:
    """Refresh the header's ``sent_at`` for a message leaving AGAIN
    (transparent resend, forward hop): without this the receiver's
    network span would absorb retry backoff and the previous silo's
    handling time — mis-attributing exactly the slow requests tracing
    exists to explain. Returns a new dict (headers may be shared)."""
    ctx = context_from_headers(request_context)
    if ctx is None:
        return request_context
    out = dict(request_context)
    out[TRACE_KEY] = (ctx[0], ctx[1], time.time())
    return out


# ---------------------------------------------------------------------------
# Critical-path breakdown
# ---------------------------------------------------------------------------

_BREAKDOWN_KEYS = ("queue", "exec", "network", "directory", "device",
                   "migration")


def critical_path_breakdown(spans) -> dict:
    """Where a trace's wall time went, as seconds and fractions of the
    trace extent: queue wait vs. turn execution (from server-span attrs),
    network legs, directory lookups, device ticks, and migration legs.

    ``spans``: Span objects or ``to_dict`` forms, typically one trace
    (pre-filter by trace_id) but tolerant of mixed input — the management
    grain feeds it the cluster-wide merge. Fractions can overlap (a
    directory RPC's network leg counts in both) and need not sum to 1;
    each answers "how much of the trace extent did this layer occupy".
    """
    dicts = [s if isinstance(s, dict) else s.to_dict() for s in spans]
    if not dicts:
        return {"total_s": 0.0, "span_count": 0,
                "seconds": {k: 0.0 for k in _BREAKDOWN_KEYS},
                "fractions": {k: 0.0 for k in _BREAKDOWN_KEYS}}
    t0 = min(s["start"] for s in dicts)
    t1 = max(s["start"] + s["duration"] for s in dicts)
    total = max(t1 - t0, 1e-9)
    seconds = {k: 0.0 for k in _BREAKDOWN_KEYS}
    for s in dicts:
        kind = s["kind"]
        if kind == "server":
            attrs = s.get("attrs") or {}
            seconds["queue"] += attrs.get("queue_s", 0.0)
            seconds["exec"] += attrs.get("exec_s", s["duration"])
        elif kind == "network":
            seconds["network"] += s["duration"]
        elif kind == "directory":
            seconds["directory"] += s["duration"]
        elif kind in ("device", "device_tick"):
            seconds["device"] += s["duration"]
        elif kind == "migration":
            seconds["migration"] += s["duration"]
    return {
        "total_s": total,
        "span_count": len(dicts),
        "seconds": seconds,
        "fractions": {k: min(1.0, v / total) for k, v in seconds.items()},
    }
