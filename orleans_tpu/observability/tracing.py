"""End-to-end distributed request tracing (L13).

The reference's tracing story is ActivityId correlation riding message
headers plus hot-path counters (SURVEY §5 "Tracing / profiling" —
RequestContext carries the ActivityId; Message.DebugContext stamps hops).
This module grows that into a W3C-style trace/span model:

* a **trace context** ``(trace_id, parent_span_id, sent_at)`` rides the
  existing ``RequestContext`` message headers under :data:`TRACE_KEY`, so
  one logical request keeps one ``trace_id`` across silo hops, forwarded
  (post-migration) hops, directory RPCs, and device-tier ticks;
* spans are opened automatically at the call sites the runtime owns —
  client invoke (``runtime_client``), server turn with queue-wait vs.
  execution split (``runtime/dispatcher``), the network leg (stamped
  send-side, measured receive-side), directory lookups
  (``directory/locator``), device ticks (``dispatch/engine``, bridged to
  ``jax.profiler.TraceAnnotation`` so XLA kernels nest under the logical
  span), and rebalance migration legs (``rebalance/executor``);
* a per-silo :class:`SpanCollector` ring buffer holds finished spans with
  a head-based sampling knob (``config.TracingOptions`` /
  ``trace_sample_rate``): the ROOT of a trace rolls the sampling die once
  and unsampled requests carry no header and record nothing downstream —
  at ``sample_rate=0`` the hot path pays one attribute check per call
  (guarded by ``tests/test_perf_floors.py::test_floor_trace_overhead``).

Consumers: the management surface (``SiloControl.ctl_trace_spans`` +
``ManagementGrain.get_trace_breakdown``) for cluster-wide critical-path
queries, and :mod:`orleans_tpu.observability.export` for Chrome-trace/
Perfetto timeline files merging every silo of a cluster.

**Tail-based retention** (the Dapper/OTel-collector tail-sampling stage):
head sampling decides what gets *recorded*; in tail mode
(``TracingOptions.tail_enabled``) the keep/drop decision is deferred until
the trace is *complete*. Closed spans buffer per-trace in a bounded
pending map; when the ROOT span closes the trace enters a quiescence
window (``tail_window``) so straggler legs — response network spans,
device ticks — still join, then a pluggable :class:`RetentionPolicy`
keeps only traces that are slow (absolute or percentile threshold),
errored, or explicitly force-retained. Kept traces promote into the
retained ring buffer (what ``snapshot``/``ctl_trace_spans``/export see)
and stream to any attached sinks (:class:`~.export.OtlpSink`); dropped
traces just bump a counter. Legs of a trace rooted *elsewhere* (a remote
silo holds only server/network spans) are buffered too: the rooting
collector pulls them at retention time through ``remote_fetcher`` (the
silo wires the ``ctl_trace_spans`` control path there), which promotes
them on the remote side via :meth:`SpanCollector.pull`; un-pulled legs
expire after ``leg_ttl`` and count dropped.

Span ``start`` times are wall-clock (``time.time()``) so spans from
different silos/processes merge onto one timeline; durations are measured
with the monotonic clock.
"""

from __future__ import annotations

import asyncio
import bisect
import contextvars
import logging
import random
import time
from collections import deque

__all__ = [
    "TRACE_KEY", "Span", "SpanCollector", "current_trace",
    "new_trace_id", "new_span_id", "critical_path_breakdown",
    "RetentionPolicy", "LatencyErrorPolicy", "span_from_dict",
    "mark_remote_if_traced", "arm_root_link", "pending_root_link",
]

log = logging.getLogger("orleans.tracing")

# RequestContext/message-header key the trace context rides under (the
# ActivityId header analog): (trace_id, parent_span_id, sent_at_wall).
# Present if and only if the trace is sampled — head-based sampling.
TRACE_KEY = "orleans.trace"

# The span context ambient to the running turn/callsite: (trace_id,
# span_id) of the span any nested outgoing call should parent under.
# None outside sampled traces (the common case — one ContextVar.get on
# the send path is the whole cost of disabled tracing there).
current_trace: contextvars.ContextVar[tuple[int, int] | None] = (
    contextvars.ContextVar("orleans_current_trace", default=None)
)

# The arming context for deferred work (span links): a timer/reminder/
# stream registration that happens inside a traced turn records
# (trace_id, span_id) here before the deferred callback runs; when that
# callback's outgoing calls ROOT a fresh trace, the new root carries the
# arming context as a span LINK — Perfetto/OTLP show causality without
# merging the two traces. None (the default) everywhere else: roots of
# ordinary client calls pay one ContextVar.get.
pending_root_link: contextvars.ContextVar[tuple[int, int] | None] = (
    contextvars.ContextVar("orleans_pending_root_link", default=None)
)


def arm_root_link(link: tuple[int, int] | None) -> None:
    """Declare the arming context for work the CURRENT task triggers:
    new roots opened downstream link back to ``link``. Pass None to
    clear (e.g. a stream pump switching to an unlinked subscription)."""
    pending_root_link.set(link)

# span kinds a collector records; critical_path_breakdown buckets by these
# ("event" is the zero-duration annotation kind — rejections, forward hops —
# which the breakdown deliberately ignores; "ring" is the shm staging/
# response ring dwell between a worker process and the device owner —
# network-style, stamped push-side and observed pop-side, but bucketed
# separately so the cross-process hop is attributable on its own)
SPAN_KINDS = ("client", "server", "network", "directory", "device",
              "device_tick", "migration", "ring", "event")


def new_trace_id() -> int:
    """63-bit random id (unique across silos without coordination)."""
    return random.getrandbits(63) or 1


def new_span_id() -> int:
    return random.getrandbits(63) or 1


class Span:
    """One timed operation. ``start`` is wall-clock seconds; ``duration``
    is a monotonic-clock delta (set by :meth:`SpanCollector.close`)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "kind",
                 "silo", "start", "duration", "attrs", "events", "links",
                 "_t0")

    def __init__(self, trace_id: int, span_id: int, parent_id: int | None,
                 name: str, kind: str, silo: str, start: float,
                 duration: float = 0.0, attrs: dict | None = None,
                 events: list | None = None,
                 links: list | None = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.silo = silo
        self.start = start
        self.duration = duration
        self.attrs = attrs
        self.events = events
        # span links: [(trace_id, span_id), ...] — causal references to
        # OTHER traces (the arming context of timer/reminder/stream-
        # triggered roots). None for the common unlinked span.
        self.links = links
        self._t0 = 0.0

    def add_event(self, name: str, **attrs) -> None:
        """Timestamped annotation on a still-open span (the OTel span-event
        analog): rejections, transient resends, forward hops. Wall-clock
        stamped so events line up with span starts on a merged timeline."""
        if self.events is None:
            self.events = []
        self.events.append([name, time.time(), attrs])

    def to_dict(self) -> dict:
        """Wire/JSON form (what ``ctl_trace_spans`` and the exporter see).
        ``events`` appears only when present, keeping the common shape —
        and the socket-wire payload — unchanged for event-less spans."""
        d = {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "kind": self.kind, "silo": self.silo, "start": self.start,
            "duration": self.duration, "attrs": self.attrs or {},
        }
        if self.events:
            d["events"] = self.events
        if self.links:
            d["links"] = [list(lk) for lk in self.links]
        return d

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return (f"<Span {self.kind} {self.name!r} {self.duration * 1e3:.3f}ms"
                f" trace={self.trace_id:x}>")


def span_from_dict(d: dict) -> Span:
    """Rehydrate a ``to_dict`` form (remote legs pulled over the control
    path arrive as dicts) back into a :class:`Span`."""
    return Span(d["trace_id"], d["span_id"], d.get("parent_id"),
                d["name"], d["kind"], d.get("silo") or "?",
                d["start"], d.get("duration", 0.0),
                dict(d.get("attrs") or {}) or None,
                list(d["events"]) if d.get("events") else None,
                [tuple(lk) for lk in d["links"]]
                if d.get("links") else None)


class RetentionPolicy:
    """Tail keep/drop decision over one completed trace. ``decide``
    receives the pending-trace record (``spans``, ``root``, ``error``)
    and returns ``(keep, reason)``; errored/forced traces are retained by
    the collector before the policy runs, so a policy only has to answer
    "is this trace interesting on latency grounds"."""

    def decide(self, trace: "_PendingTrace") -> tuple[bool, str | None]:
        raise NotImplementedError


class LatencyErrorPolicy(RetentionPolicy):
    """Default policy: keep traces whose root latency exceeds an absolute
    threshold (``slow_threshold`` seconds; <=0 disables) or a percentile
    of recently completed root latencies (``slow_percentile`` in (0,1);
    0 disables; needs a small warm-up history before it fires). A trace
    with no root span locally is never slow by this policy — only the
    rooting collector sees the full round trip.

    ``auto=True`` (the ``trace_tail_auto`` knob): ``slow_threshold``
    self-tunes from the same root-duration history — each decision damps
    the threshold toward the ``slow_percentile`` cut (default 0.95 when
    unset), so a workload whose baseline latency drifts keeps retaining
    roughly the slowest ``1-p`` fraction instead of whatever a hand-set
    absolute threshold happens to straddle. Until the history warms
    (``_MIN_HISTORY`` roots) the configured static threshold applies
    unchanged; retention in auto mode is strictly-above so a uniform
    workload converges to retaining nothing, not everything."""

    _AUTO_PERCENTILE = 0.95  # default cut when slow_percentile unset
    _AUTO_DAMPING = 0.2      # per-decision step toward the current cut

    def __init__(self, slow_threshold: float = 0.1,
                 slow_percentile: float = 0.0, history: int = 512,
                 auto: bool = False):
        self.slow_threshold = slow_threshold
        self.slow_percentile = slow_percentile
        self.auto = auto
        self._durations: deque[float] = deque(maxlen=history)
        self._ranked: list[float] = []  # sorted twin, maintained via bisect

    _MIN_HISTORY = 16  # percentile over fewer samples is noise

    def _observe(self, dur: float) -> None:
        # maintained sorted twin: one insort + one bisect-delete per
        # trace instead of re-sorting the whole history each decision
        if len(self._durations) == self._durations.maxlen:
            old = self._durations.popleft()
            del self._ranked[bisect.bisect_left(self._ranked, old)]
        self._durations.append(dur)
        bisect.insort(self._ranked, dur)

    def decide(self, trace: "_PendingTrace") -> tuple[bool, str | None]:
        root = trace.root
        if root is None:
            return False, None
        dur = root.duration
        if self.auto:
            self._observe(dur)
            n = len(self._ranked)
            if n >= self._MIN_HISTORY:
                p = self.slow_percentile or self._AUTO_PERCENTILE
                cut = self._ranked[min(n - 1, int(p * n))]
                t = self.slow_threshold
                self.slow_threshold = cut if t <= 0 else \
                    t + self._AUTO_DAMPING * (cut - t)
            # strictly above: the threshold converges onto the cut, and a
            # uniform workload (dur == cut) must not tail-retain everything
            if self.slow_threshold > 0 and dur > self.slow_threshold:
                return True, "slow_auto"
            return False, None
        if self.slow_threshold > 0 and dur >= self.slow_threshold:
            return True, "slow"
        p = self.slow_percentile
        if p > 0:
            self._observe(dur)
            n = len(self._ranked)
            if n >= self._MIN_HISTORY:
                cut = self._ranked[min(n - 1, int(p * n))]
                # strictly above: a uniform workload (every duration equal
                # to the cut) must not tail-retain everything
                if dur > cut:
                    return True, "slow_pctl"
        return False, None


class _PendingTrace:
    """Spans of one not-yet-decided trace buffered in tail mode."""

    __slots__ = ("spans", "root", "root_closed_mono", "last_mono",
                 "error", "force", "remote")

    def __init__(self, now: float):
        self.spans: list[Span] = []
        self.root: Span | None = None
        self.root_closed_mono: float | None = None
        self.last_mono = now
        self.error = False
        self.force = False
        # "went remote" hint (mark_remote): any leg of this trace left the
        # local process, so retention must pull peers before export. False
        # = provably silo-local — the ctl_trace_spans fan-out is skipped.
        self.remote = False


class SpanCollector:
    """Per-silo (or per-client) span sink: bounded ring buffer + the
    head-based sampling decision. Cheap enough for the hot path — an
    unsampled call never reaches it, and a sampled span costs two clock
    reads, one random id, and a deque append (plus, in tail mode, one
    dict get and a list append into the pending buffer)."""

    def __init__(self, name: str, sample_rate: float = 1.0,
                 buffer_size: int = 4096, *, tail: bool = False,
                 tail_window: float = 0.25,
                 policy: RetentionPolicy | None = None,
                 leg_ttl: float = 2.0, max_pending: int = 256):
        self.name = name
        self.sample_rate = sample_rate
        self.spans: deque[Span] = deque(maxlen=buffer_size)
        # synthetic trace grouping device ticks not tied to one request
        self.device_trace_id = new_trace_id()
        # one-shot pre-rolled head-sampling decision (the hot lane rolls
        # the die itself and hands the outcome to the messaging path so
        # the rate is never squared nor doubled): None = not rolled,
        # True/False = rolled, consume instead of re-rolling
        self.presampled: bool | None = None
        # -- tail-based retention (off: none of this is touched) ----------
        self.tail = tail
        self.tail_window = tail_window
        self.policy = policy or LatencyErrorPolicy()
        self.leg_ttl = leg_ttl
        self.max_pending = max_pending
        self.pending: dict[int, _PendingTrace] = {}
        # streaming exporters (export.OtlpSink shape: offer/flush/aclose)
        self.sinks: list = []
        # async ``fetch(trace_id) -> list[span dict]`` pulling remote legs
        # of a trace this collector retained (silo: ctl_trace_spans fan-out)
        self.remote_fetcher = None
        # ``fn(root_span | None, reason)`` called once per RETAINED trace
        # before export — the silo wires the flight recorder here so a
        # tail-retained slow trace snapshots the loop-occupancy ring it
        # was slow IN (and may stamp attrs on the root before it ships)
        self.on_retain = None
        self._ret = {"kept": 0, "dropped": 0, "pulled": 0,
                     "pull_skipped": 0}
        # insertion-ordered so the bound evicts the OLDEST pin, not all
        self._forced: dict[int, None] = {}
        # "went remote" hints for traces with no pending entry yet (the
        # root span usually closes LAST, after the outbound send that
        # proves remoteness) — bounded, oldest-evicted like _forced
        self._remote_hints: dict[int, None] = {}
        self._tasks: set = set()
        self._sweeper = None
        self._pump_at = 0.0

    # -- sampling (root decision; propagated via header presence) --------
    def sample(self) -> bool:
        r = self.sample_rate
        if r >= 1.0:
            return True
        if r <= 0.0:
            return False
        return random.random() < r

    def consume_head_roll(self) -> bool:
        """The root sampling decision, honoring a die already rolled by
        the hot lane this same synchronous step (see ``presampled``)."""
        p = self.presampled
        if p is not None:
            self.presampled = None
            return p
        return self.sample()

    def new_trace_id(self) -> int:
        return new_trace_id()

    # -- span lifecycle ---------------------------------------------------
    def open(self, name: str, kind: str, trace_id: int,
             parent_id: int | None) -> Span:
        span = Span(trace_id, new_span_id(), parent_id, name, kind,
                    self.name, time.time())
        span._t0 = time.monotonic()
        return span

    def close(self, span: Span, duration: float | None = None,
              **attrs) -> Span:
        span.duration = (time.monotonic() - span._t0
                         if duration is None else duration)
        if attrs:
            span.attrs = attrs
        self._ingest(span)
        return span

    def record(self, trace_id: int, parent_id: int | None, name: str,
               kind: str, start: float, duration: float, **attrs) -> Span:
        """Record a span whose timing was measured externally (e.g. the
        network leg: stamped send-side, observed receive-side)."""
        span = Span(trace_id, new_span_id(), parent_id, name, kind,
                    self.name, start, max(0.0, duration), attrs or None)
        self._ingest(span)
        return span

    def event(self, trace_id: int, parent_id: int | None, name: str,
              **attrs) -> Span:
        """Zero-duration annotation span (kind ``event``) for call sites
        that only know the trace/span IDS of the active invoke span, not
        the Span object — dispatcher-side rejections and forward hops.
        Parents under the given span so it lands inside the invoke/turn
        in the trace tree; the critical-path breakdown ignores it."""
        return self.record(trace_id, parent_id, name, "event",
                           time.time(), 0.0, **attrs)

    # span-count bound per pending trace: a "trace" accumulating more was
    # never going to be a useful retention unit (and an unbounded spans
    # list is a memory hazard) — drop the whole entry, count it
    _MAX_TRACE_SPANS = 1024

    # -- tail retention stage ---------------------------------------------
    def _ingest(self, span: Span) -> None:
        if not self.tail or span.trace_id == self.device_trace_id:
            # the synthetic device-tick trace bypasses the tail stage even
            # in tail mode: its parent-less tick spans arrive forever (each
            # would re-arm the quiescence window, so the pending entry
            # could never finalize and would grow without bound) and tick
            # telemetry is not a request whose tail matters — it lands in
            # the bounded ring. Deliberately NOT offered to sinks in tail
            # mode (head mode streams every span): tail exists to cut
            # export volume, and full-rate tick telemetry would flood the
            # collector; the ring/management surface still serves it.
            self.spans.append(span)
            if self.sinks and not self.tail:
                d = (span.to_dict(),)
                for s in self.sinks:
                    s.offer(d)
            return
        now = time.monotonic()
        e = self.pending.get(span.trace_id)
        if e is None:
            if len(self.pending) >= self.max_pending:
                # bounded memory: evict the oldest undecided trace. A
                # root-closed victim gets its tail decision NOW (window cut
                # short) — overload is exactly when the errored/slow traces
                # retention exists for show up, so they must not shed
                # undecided; leg-only victims just count dropped.
                tid = next(iter(self.pending))
                victim = self.pending.pop(tid)
                if victim.root_closed_mono is not None:
                    self._finalize(tid, victim)
                else:
                    self._ret["dropped"] += 1
            e = self.pending[span.trace_id] = _PendingTrace(now)
            if self._remote_hints.pop(span.trace_id, 0) is None:
                # a send-side hook marked this trace remote before any of
                # its spans closed locally (stored value is None; miss is 0)
                e.remote = True
        elif len(e.spans) >= self._MAX_TRACE_SPANS and \
                span.parent_id is not None:
            # cap the entry but KEEP it so the trace still gets exactly one
            # tail decision: non-root spans past the bound are discarded
            # (truncated telemetry beats unbounded memory); the root always
            # lands, or the decision/quiescence would never trigger. The
            # ERROR signal survives even when the span doesn't — a failing
            # leg past the cap must still make the trace retainable.
            e.last_mono = now
            attrs = span.attrs
            if attrs is not None and "error" in attrs:
                e.error = True
            log.debug("tail trace %x exceeded %d spans; truncating",
                      span.trace_id, self._MAX_TRACE_SPANS)
            return
        e.spans.append(span)
        e.last_mono = now
        attrs = span.attrs
        if attrs is not None and "error" in attrs:
            e.error = True
        if span.parent_id is None:
            # root closed: quiescence window starts — stragglers (response
            # network legs, device ticks) join until it elapses
            e.root = span
            e.root_closed_mono = now
        self._ensure_sweeper()
        if now >= self._pump_at:  # amortized: don't scan per span
            self._pump_at = now + max(0.02, self.tail_window / 4)
            self._pump(now)

    def _pump(self, now: float, force: bool = False,
              expire_legs: bool | None = None) -> None:
        """Finalize quiesced root-closed traces; expire never-rooted legs.
        ``force`` decides root-closed traces immediately; ``expire_legs``
        (defaults to ``force``) drops leg-only traces now — kept separate
        so a cluster-wide drain can settle every collector's roots (and
        their cross-silo pulls) BEFORE any collector expires legs a peer's
        pull still needs."""
        if expire_legs is None:
            expire_legs = force
        done: list[tuple[int, _PendingTrace]] = []
        for tid, e in self.pending.items():
            if e.root_closed_mono is not None:
                if force or now - e.root_closed_mono >= self.tail_window:
                    done.append((tid, e))
            elif expire_legs or now - e.last_mono >= self.leg_ttl:
                done.append((tid, e))
        for tid, e in done:
            del self.pending[tid]
            if e.root_closed_mono is None:
                # legs of a trace rooted elsewhere, never pulled: the
                # rooting collector dropped it (or died) — expire
                self._ret["dropped"] += 1
                self._forced.pop(tid, None)
                self._remote_hints.pop(tid, None)
                continue
            self._finalize(tid, e)

    def _finalize(self, tid: int, e: _PendingTrace) -> None:
        keep, reason = True, None
        if e.force or tid in self._forced:
            reason = "forced"
        elif e.error:
            reason = "error"
        else:
            keep, reason = self.policy.decide(e)
        self._forced.pop(tid, None)
        went_remote = e.remote or \
            self._remote_hints.pop(tid, 0) is None
        if not keep:
            self._ret["dropped"] += 1
            return
        if self.remote_fetcher is not None:
            if not went_remote:
                # silo-local trace (no leg ever left this process): every
                # span is already here — skip the ctl_trace_spans fan-out
                # to every peer, which would return nothing and cost one
                # SYSTEM RPC per silo per retained trace
                self._ret["pull_skipped"] += 1
            else:
                try:
                    loop = asyncio.get_running_loop()
                except RuntimeError:
                    loop = None
                if loop is not None:
                    t = loop.create_task(
                        self._retain_with_pull(tid, e, reason))
                    self._tasks.add(t)
                    t.add_done_callback(self._tasks.discard)
                    return
        self._commit(e.spans, (), reason, e.root)

    async def _retain_with_pull(self, tid: int, e: _PendingTrace,
                                reason: str | None) -> None:
        """Retention propagation: this collector decided to keep the
        trace, but cross-silo legs live in other collectors' pending
        buffers — pull them (ctl_trace_spans path) before committing, so
        the exported trace is whole."""
        # the task copied its creator's context — which can hold a LIVE
        # ambient trace (finalize can run from _ingest inside a traced
        # turn). The pull RPC must not join it: phantom control-path spans
        # would pollute an unrelated trace's tree. SYSTEM calls never ROOT
        # traces, but they do join ambient ones — so clear it here.
        current_trace.set(None)
        remote: list[dict] = []
        try:
            remote = list(await self.remote_fetcher(tid)) or []
        except Exception as ex:  # noqa: BLE001 — export best-effort
            log.debug("remote leg pull failed for trace %x: %s", tid, ex)
        seen = {s.span_id for s in e.spans}
        remote = [d for d in remote if d.get("span_id") not in seen]
        self._commit(e.spans, remote, reason, e.root)

    def _commit(self, spans: list[Span], remote_dicts, reason,
                root: Span | None) -> None:
        self._ret["kept"] += 1
        if reason is not None and root is not None:
            root.attrs = dict(root.attrs or {})
            root.attrs["retained"] = reason
        if self.on_retain is not None:
            # BEFORE the sink batch is built: the hook may stamp attrs on
            # the root (flight-snapshot marker) that must ride the export
            try:
                self.on_retain(root, reason)
            except Exception:  # noqa: BLE001 — a hook must not break commit
                log.exception("on_retain hook failed")
        self.spans.extend(spans)
        remote_spans = [span_from_dict(d) for d in remote_dicts]
        self.spans.extend(remote_spans)
        if self.sinks:
            batch = [s.to_dict() for s in spans] + list(remote_dicts)
            for s in self.sinks:
                s.offer(batch)

    def pull(self, trace_id: int, limit: int | None = None) -> list[dict]:
        """Remote-retention hand-off: return every span this collector
        holds for ``trace_id`` — retained ring AND pending buffer — and
        HAND OFF pending LEG-ONLY entries (legs of a trace rooted
        elsewhere: the puller decided the trace matters and becomes their
        owner of record). Handed-off legs count kept/pulled here — the
        cluster-wide decision was "keep" — but are NOT copied into this
        ring nor offered to this collector's sinks: exactly one collector
        (the pulling one) stores and exports the merged trace, so
        cluster-wide span merges never double-count. An entry rooted HERE
        is returned read-only and stays pending: its own tail decision
        (and sink export, if kept) must still run — a puller peeking at a
        live trace id must not steal it from the export path."""
        out = [s.to_dict() for s in self.spans if s.trace_id == trace_id]
        e = self.pending.get(trace_id)
        if e is not None:
            if e.root_closed_mono is None:
                del self.pending[trace_id]
                self._ret["kept"] += 1
                self._ret["pulled"] += 1
                self._forced.pop(trace_id, None)
                self._remote_hints.pop(trace_id, None)
            out.extend(s.to_dict() for s in e.spans)
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def mark_remote(self, trace_id: int) -> None:
        """Record that a leg of ``trace_id`` left this process (stamped by
        the send paths: MessageCenter egress, client transmit). Retention
        only fans ``ctl_trace_spans`` out to peers for marked traces —
        silo-local traces skip the pull entirely (``pull_skipped``)."""
        if not self.tail:
            return
        e = self.pending.get(trace_id)
        if e is not None:
            e.remote = True
            return
        if trace_id in self._remote_hints:
            return
        if len(self._remote_hints) >= 4096:
            # bounded: evict the OLDEST hint — a lost hint degrades to a
            # skipped pull (best-effort completeness), never an error
            self._remote_hints.pop(next(iter(self._remote_hints)))
        self._remote_hints[trace_id] = None

    def force_retain(self, trace_id: int) -> None:
        """Pin a trace through the tail decision regardless of policy
        (operator 'keep whatever this request does' hook)."""
        e = self.pending.get(trace_id)
        if e is not None:
            e.force = True
            return
        if len(self._forced) >= 4096:
            # bounded: evict the OLDEST pin only — clearing wholesale
            # would silently unpin every live operator hold at once
            self._forced.pop(next(iter(self._forced)))
        self._forced[trace_id] = None

    def _ensure_sweeper(self) -> None:
        s = self._sweeper
        if s is not None and not s.done():
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # loop-less (unit tests): lazy pump via _ingest/flush
        self._sweeper = loop.create_task(self._sweep())

    async def _sweep(self) -> None:
        # idle-exit loop: runs only while traces are pending; the next
        # _ingest restarts it. Period is fine-grained enough that a trace
        # finalizes within ~1.5 windows of its root closing.
        # Clear any inherited ambient trace (the task can be created from
        # inside a traced turn): pulls triggered by this sweeper must not
        # join — and permanently pin — whatever trace was live at spawn.
        current_trace.set(None)
        from .profiling import mark_loop_category
        mark_loop_category("observability")  # sweeper steps are our tax
        period = max(0.01, min(self.tail_window, self.leg_ttl) / 2)
        while self.pending:
            await asyncio.sleep(period)
            self._pump(time.monotonic())

    def flush_tail(self, force: bool = False,
                   expire_legs: bool | None = None) -> None:
        """Synchronously run the tail decision for quiesced traces
        (``force=True``: decide root-closed traces now; ``expire_legs``
        defaults to ``force`` — see :meth:`_pump`). Remote pulls still
        complete asynchronously — use :meth:`drain_tail` to await them."""
        if self.tail:
            self._pump(time.monotonic(), force=force,
                       expire_legs=expire_legs)

    async def drain_tail(self, force: bool = True,
                         expire_legs: bool | None = None) -> None:
        """Decide + commit everything pending, await in-flight pulls, and
        flush sinks — the deterministic settle point for tests/teardown."""
        self.flush_tail(force=force, expire_legs=expire_legs)
        while self._tasks:
            # snapshot-and-remove: gather over already-done tasks resolves
            # without yielding, so waiting on the discard callbacks alone
            # could spin — remove what we await ourselves
            tasks = list(self._tasks)
            self._tasks.difference_update(tasks)
            await asyncio.gather(*tasks, return_exceptions=True)
        for s in self.sinks:
            await s.flush()

    async def aclose(self, flush: bool = True) -> None:
        """Teardown: graceful (decide + export what's buffered) or abrupt
        (drop pending, cancel tasks). Sinks close either way."""
        if self.tail:
            if flush:
                await self.drain_tail(force=True)
            else:
                self.pending.clear()
                for t in list(self._tasks):
                    t.cancel()
        s = self._sweeper
        if s is not None and not s.done():
            s.cancel()
        self._sweeper = None
        for sink in self.sinks:
            await sink.aclose(flush=flush)

    def retention_stats(self) -> dict:
        """Tail/export counters (kept/dropped/pulled/buffered + sink
        exported/dropped sums) — the management-surface payload."""
        out = {
            "tail": self.tail,
            "kept": self._ret["kept"],
            "dropped": self._ret["dropped"],
            "pulled": self._ret["pulled"],
            "pull_skipped": self._ret["pull_skipped"],
            "buffered": len(self.pending),
            "retained_spans": len(self.spans),
            "exported": 0, "export_dropped": 0,
        }
        for s in self.sinks:
            st = s.stats()
            out["exported"] += st.get("exported", 0)
            out["export_dropped"] += st.get("export_dropped", 0)
        return out

    # -- reads -------------------------------------------------------------
    def snapshot(self, trace_id: int | None = None,
                 limit: int | None = None) -> list[dict]:
        spans = list(self.spans)
        if trace_id is not None:
            spans = [s for s in spans if s.trace_id == trace_id]
            if self.pending:
                # a specific trace's pending (undecided) legs are visible
                # read-only — diagnostics must not wait out the window
                e = self.pending.get(trace_id)
                if e is not None:
                    spans.extend(e.spans)
        if limit is not None and len(spans) > limit:
            spans = spans[-limit:]
        return [s.to_dict() for s in spans]

    def trace_ids(self) -> list[int]:
        seen: dict[int, None] = {}
        for s in self.spans:
            seen.setdefault(s.trace_id, None)
        return list(seen)

    def clear(self) -> None:
        self.spans.clear()
        self.pending.clear()
        self._remote_hints.clear()


def context_from_headers(request_context: dict | None
                         ) -> tuple[int, int, float] | None:
    """Extract ``(trace_id, parent_span_id, sent_at)`` from message
    baggage; None when the request is untraced/unsampled OR the header is
    malformed. RequestContext is app-writable, so every runtime consumer
    parses through this single hardened path — garbage baggage must never
    break a turn or drop a message, it just goes untraced."""
    if not request_context:
        return None
    hdr = request_context.get(TRACE_KEY)
    if hdr is None:
        return None
    try:
        # tolerate list-decoded tuples from portable codecs
        t, p, s = hdr
        return (int(t), int(p), float(s))
    except (TypeError, ValueError):
        return None


def mark_remote_if_traced(tracer, msg) -> None:
    """Stamp the "went remote" retention hint for a traced message about
    to leave its process — the ONE implementation behind every send-side
    hook (silo fabric egress in MessageCenter.send_message; client
    transmits in ClusterClient/GatewayClient). No-op outside tail mode
    or for untraced messages; hardened header parsing like every other
    runtime consumer of the baggage."""
    if tracer is not None and tracer.tail and msg.request_context:
        hdr = context_from_headers(msg.request_context)
        if hdr is not None:
            tracer.mark_remote(hdr[0])


def restamp_header(request_context: dict | None) -> dict | None:
    """Refresh the header's ``sent_at`` for a message leaving AGAIN
    (transparent resend, forward hop): without this the receiver's
    network span would absorb retry backoff and the previous silo's
    handling time — mis-attributing exactly the slow requests tracing
    exists to explain. Returns a new dict (headers may be shared)."""
    ctx = context_from_headers(request_context)
    if ctx is None:
        return request_context
    out = dict(request_context)
    out[TRACE_KEY] = (ctx[0], ctx[1], time.time())
    return out


# ---------------------------------------------------------------------------
# Critical-path breakdown
# ---------------------------------------------------------------------------

_BREAKDOWN_KEYS = ("queue", "exec", "network", "directory", "device",
                   "migration", "ring")


def critical_path_breakdown(spans) -> dict:
    """Where a trace's wall time went, as seconds and fractions of the
    trace extent: queue wait vs. turn execution (from server-span attrs),
    network legs, directory lookups, device ticks, and migration legs.

    ``spans``: Span objects or ``to_dict`` forms, typically one trace
    (pre-filter by trace_id) but tolerant of mixed input — the management
    grain feeds it the cluster-wide merge. Fractions can overlap (a
    directory RPC's network leg counts in both) and need not sum to 1;
    each answers "how much of the trace extent did this layer occupy".
    """
    dicts = [s if isinstance(s, dict) else s.to_dict() for s in spans]
    if not dicts:
        return {"total_s": 0.0, "span_count": 0,
                "seconds": {k: 0.0 for k in _BREAKDOWN_KEYS},
                "fractions": {k: 0.0 for k in _BREAKDOWN_KEYS}}
    t0 = min(s["start"] for s in dicts)
    t1 = max(s["start"] + s["duration"] for s in dicts)
    total = max(t1 - t0, 1e-9)
    seconds = {k: 0.0 for k in _BREAKDOWN_KEYS}
    for s in dicts:
        kind = s["kind"]
        if kind == "server":
            attrs = s.get("attrs") or {}
            seconds["queue"] += attrs.get("queue_s", 0.0)
            seconds["exec"] += attrs.get("exec_s", s["duration"])
        elif kind == "network":
            seconds["network"] += s["duration"]
        elif kind == "directory":
            seconds["directory"] += s["duration"]
        elif kind in ("device", "device_tick"):
            seconds["device"] += s["duration"]
        elif kind == "migration":
            seconds["migration"] += s["duration"]
        elif kind == "ring":
            seconds["ring"] += s["duration"]
    return {
        "total_s": total,
        "span_count": len(dicts),
        "seconds": seconds,
        "fractions": {k: min(1.0, v / total) for k, v in seconds.items()},
    }
