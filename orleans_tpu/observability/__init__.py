"""Observability: statistics, device profiling, management surface
(reference L13)."""

from .profiling import Profiler, StepTimer, annotate, traced  # noqa: F401
from .stats import Histogram, StatsRegistry  # noqa: F401
