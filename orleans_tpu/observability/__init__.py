"""Observability: statistics, device profiling, management surface
(reference L13)."""

from .profiling import Profiler, StepTimer, annotate, traced  # noqa: F401
from .stats import REBALANCE_STATS, Histogram, StatsRegistry  # noqa: F401
