"""Observability: statistics, device profiling, distributed tracing,
management surface (reference L13)."""

from .export import chrome_trace_events, write_chrome_trace  # noqa: F401
from .profiling import Profiler, StepTimer, annotate, traced  # noqa: F401
from .stats import REBALANCE_STATS, Histogram, StatsRegistry  # noqa: F401
from .tracing import (  # noqa: F401
    TRACE_KEY,
    Span,
    SpanCollector,
    critical_path_breakdown,
    current_trace,
)
