"""Observability: statistics, device profiling, distributed tracing,
management surface (reference L13)."""

from .export import (  # noqa: F401
    OtlpMetricsSink,
    OtlpSink,
    chrome_trace_events,
    snapshots_to_otlp_metrics,
    spans_to_otlp,
    write_chrome_trace,
)
from .metrics import (  # noqa: F401
    MetricsHttpServer,
    MetricsSampler,
    WindowedGauge,
    prometheus_exposition,
)
from .profiling import (  # noqa: F401
    LOOP_CATEGORIES,
    LoopProfiler,
    Profiler,
    StepTimer,
    annotate,
    install_loop_profiler,
    loop_profiler,
    mark_loop_category,
    traced,
    uninstall_loop_profiler,
)
from .slo import (  # noqa: F401
    SloMonitor,
    SloSpec,
    default_specs,
)
from .stats import (  # noqa: F401
    INGEST_STAGES,
    INGEST_STATS,
    REBALANCE_STATS,
    SLO_STATS,
    CallSiteStats,
    Histogram,
    StatsRegistry,
)
from .tracing import (  # noqa: F401
    TRACE_KEY,
    LatencyErrorPolicy,
    RetentionPolicy,
    Span,
    SpanCollector,
    critical_path_breakdown,
    current_trace,
    span_from_dict,
)
