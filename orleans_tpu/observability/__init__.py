"""Observability: statistics, management surface (reference L13)."""

from .stats import Histogram, StatsRegistry  # noqa: F401
