"""Observability: statistics, device profiling, distributed tracing,
management surface (reference L13)."""

from .export import (  # noqa: F401
    OtlpSink,
    chrome_trace_events,
    spans_to_otlp,
    write_chrome_trace,
)
from .profiling import Profiler, StepTimer, annotate, traced  # noqa: F401
from .stats import REBALANCE_STATS, Histogram, StatsRegistry  # noqa: F401
from .tracing import (  # noqa: F401
    TRACE_KEY,
    LatencyErrorPolicy,
    RetentionPolicy,
    Span,
    SpanCollector,
    critical_path_breakdown,
    current_trace,
    span_from_dict,
)
