"""Cost-attribution ledger: who is spending this silo (ISSUE 17).

The substrate can already say that it is unhealthy (slo.py burn rates)
and where loop time goes (profiling.py occupancy) — this module says
**who**: every unit of work is charged to a (grain_class, method) row,
a hashed-key label, and a tenant, across both tiers:

* **host turns** — exec + queue-wait seconds, charged in the
  dispatcher's turn epilogue and the hot lane's inline turn;
* **device ticks** — row-seconds per class (rows_in_batch × tick wall),
  charged at the engine's batch epilogue, with the per-slot twin
  accumulated ON DEVICE next to the PR-1 hit counters
  (``ShardedActorTable.record_cost``) and folded by
  ``ops.segment_reduce.masked_reduce``;
* **wire bytes** — in/out per route, charged where sizes are already
  measured (ingress pumps, egress senders, client writes);
* **stream deliveries** — the device stream provider's pump.

**Bounded by construction.** Exact totals are kept only per
(grain_class, method) row (capped, CallSiteStats-style overflow
counter); the per-key and per-tenant dimensions ride space-saving
top-K sketches (Metwally et al.: evicting the min entry charges its
count to the newcomer as ``err``), so a million-actor silo costs O(K)
memory and the cluster merge is a deterministic flat fold.

**Thread contract.** Like every registry in this package the ledger is
loop-confined: plain dicts, no locks. Off-loop producers (the tick
worker, ingress/egress shards) stamp charge payloads into plain lists
and replay them loop-side — engine._complete_job and the shard stat
rings carry the stamps, the OTPU007 rule verifies the discipline.

**Tenancy.** The tenant of a charge comes from the ``tenant_of`` config
hook (label → tenant, covers batched device traffic, which carries no
per-call context) or, for host turns, the ``orleans.tenant``
RequestContext baggage tag the caller attached.
"""

from __future__ import annotations

from typing import Callable, Iterable

__all__ = ["CostLedger", "SpaceSavingSketch", "LEDGER_STATS",
           "TENANT_KEY", "WIRE_STAMP"]

# cross-thread wire-charge stamp sentinel: egress shards may not touch
# the loop-confined ledger, so they append ``(WIRE_STAMP, (route,
# nbytes))`` to their stat-ring stamps and the main-loop drain replays
# the charge (the engine's _LEDGER tick stamp, for the wire tier)
WIRE_STAMP = object()

# RequestContext baggage key carrying the caller's tenant (the TXN_KEY
# naming pattern): read in the turn epilogue BEFORE the context clears
TENANT_KEY = "orleans.tenant"

# exact per-(class, method) rows kept before overflow counting starts
# (the CallSiteStats cap discipline: first-come rows stay exact)
_MAX_ROWS = 512

LEDGER_STATS = {
    "turn_seconds": "ledger.turn.seconds",
    "queue_seconds": "ledger.queue.seconds",
    "row_seconds": "ledger.device.row_seconds",
    "wire_rx": "ledger.wire.rx_bytes",
    "wire_tx": "ledger.wire.tx_bytes",
    "stream_deliveries": "ledger.streams.delivered",
    "charges": "ledger.charges",
    "tracked_keys": "ledger.keys.tracked",
    "key_overflow": "ledger.keys.overflow",
}


class SpaceSavingSketch:
    """Bounded heavy-hitter counter (space-saving, Metwally et al.).

    At most ``k`` tracked labels. A charge to an untracked label while
    full evicts the minimum entry: the newcomer inherits the evicted
    count as both its starting count and its ``err`` bound (true count
    ∈ [count - err, count]), and ``overflow`` counts evictions. The
    guarantee this buys: any label whose true total exceeds total/k is
    present — exactly the "name the hot key" contract the SLO
    drill-down needs, at O(k) memory for any key cardinality.
    """

    __slots__ = ("k", "counts", "overflow")

    def __init__(self, k: int):
        self.k = max(1, int(k))
        # label -> [count, err]; labels are plain strings so snapshots
        # survive the management wire without key re-encoding
        self.counts: dict[str, list[float]] = {}
        self.overflow = 0

    def add(self, label: str, amount: float = 1.0) -> None:
        c = self.counts.get(label)
        if c is not None:
            c[0] += amount
            return
        if len(self.counts) < self.k:
            self.counts[label] = [amount, 0.0]
            return
        victim = min(self.counts, key=self._min_key)
        floor = self.counts.pop(victim)[0]
        self.overflow += 1
        self.counts[label] = [floor + amount, floor]

    def _min_key(self, label: str):
        # deterministic eviction: ties on count break on the label, so
        # two silos fed identical streams evict identically
        return (self.counts[label][0], label)

    def top(self, k: int | None = None) -> list[tuple[str, float, float]]:
        """[(label, count, err)] sorted by (-count, label) — the
        deterministic ranking every surface shows."""
        rows = sorted(((label, c[0], c[1])
                       for label, c in self.counts.items()),
                      key=lambda r: (-r[1], r[0]))
        return rows if k is None else rows[:k]

    def snapshot(self) -> dict:
        return {"k": self.k, "overflow": self.overflow,
                "counts": {label: list(c)
                           for label, c in self.counts.items()}}

    @staticmethod
    def merge(snapshots: Iterable[dict], k: int | None = None) -> dict:
        """Deterministic flat merge: sum (count, err) per label across
        ALL snapshots at once, keep the top-k by (-count, label).
        Order-independence falls out of the commutative sums + total
        ranking — merging 4 silos pairwise or flat gives one answer
        (property-tested). A dropped label's count lands in ``err``
        semantics implicitly: dropping is counted in ``overflow``."""
        snapshots = list(snapshots)
        if k is None:
            k = max((int(s.get("k", 1)) for s in snapshots), default=1)
        per: dict[str, list[tuple[float, float]]] = {}
        overflow = 0
        for s in snapshots:
            overflow += int(s.get("overflow", 0))
            for label, (count, err) in s.get("counts", {}).items():
                per.setdefault(label, []).append((float(count), float(err)))
        # canonicalize float-add order per label: the merged counts are
        # bit-identical no matter which order the per-silo snapshots
        # arrived in (the order-independence the property test pins)
        acc: dict[str, list[float]] = {}
        for label, contribs in per.items():
            contribs.sort()
            acc[label] = [sum(c for c, _ in contribs),
                          sum(e for _, e in contribs)]
        ranked = sorted(acc.items(), key=lambda kv: (-kv[1][0], kv[0]))
        overflow += max(0, len(ranked) - k)
        return {"k": k, "overflow": overflow,
                "counts": {label: c for label, c in ranked[:k]}}


class CostLedger:
    """Per-silo cost accounting (loop-confined; see module docstring).

    ``tenant_of``: optional label → tenant hook; host-turn charges fall
    back to the caller's ``orleans.tenant`` RequestContext baggage.
    """

    def __init__(self, top_k: int = 32,
                 tenant_of: "Callable[[str], str | None] | None" = None):
        self.top_k = max(1, int(top_k))
        self.tenant_of = tenant_of
        # exact per-(interface, method) rows:
        # [calls, exec_seconds, queue_seconds]
        self.turns: dict[tuple[str, str], list[float]] = {}
        # exact per-(class, method) device rows:
        # [batches, rows, row_seconds]
        self.device: dict[tuple[str, str], list[float]] = {}
        self.row_overflow = 0          # charges past the _MAX_ROWS cap
        self.wire: dict[str, list[int]] = {}   # route -> [rx, tx]
        # exact per-originating-process device rows (the cross-process
        # attribution of ISSUE 20): origin label ("worker-N") ->
        # [rows, row_seconds] — which worker's traffic is burning the
        # owner's device. Fed by the engine payload's optional origins
        # column; empty in single-process silos.
        self.procs: dict[str, list[float]] = {}
        self.streams: dict[str, int] = {}      # namespace -> deliveries
        self.keys = SpaceSavingSketch(self.top_k)     # label -> seconds
        self.tenants = SpaceSavingSketch(self.top_k)  # tenant -> seconds
        self.charges = 0               # charge calls accepted (all verbs)
        # bound once: a per-charge `from ..runtime import` re-resolves
        # the module on every turn (~1.4 us — more than the rest of the
        # charge combined); construction happens post-import, so the
        # late bind here cannot cycle
        from ..runtime.context import RequestContext
        self._baggage_get = RequestContext.get

    # -- tenancy --------------------------------------------------------
    def _tenant(self, label: str | None, baggage: bool) -> str | None:
        if label is not None and self.tenant_of is not None:
            t = self.tenant_of(label)
            if t is not None:
                return t
        if baggage:
            return self._baggage_get(TENANT_KEY)
        return None

    def _charge_key(self, label: str | None, seconds: float,
                    baggage: bool) -> None:
        if label is None:
            return
        self.keys.add(label, seconds)
        tenant = self._tenant(label, baggage)
        if tenant is not None:
            self.tenants.add(str(tenant), seconds)

    # -- charge verbs (each one loop-side; off-loop producers stamp) ----
    def charge_turn(self, interface: str, method: str, exec_s: float,
                    queue_s: float = 0.0, key: str | None = None) -> None:
        """One host turn (dispatcher epilogue / hot-lane inline turn).
        ``key``: the grain label ("Class/key") for the per-key sketch."""
        self.charges += 1
        row = self.turns.get((interface, method))
        if row is not None:
            row[0] += 1
            row[1] += exec_s
            row[2] += queue_s
        elif len(self.turns) < _MAX_ROWS:
            self.turns[(interface, method)] = [1, exec_s, queue_s]
        else:
            self.row_overflow += 1
        self._charge_key(key, exec_s + queue_s, baggage=True)

    def charge_tick(self, payload: tuple) -> None:
        """One device tick, as stamped by the engine:
        ``(cls_name, method, rows, tick_seconds, key_labels[, origins])``
        — row-seconds = rows × tick wall; each key label is charged its
        per-row share. Batched traffic carries no per-call baggage, so
        tenancy comes from the ``tenant_of`` hook only. The optional
        ``origins`` column (parallel to ``key_labels``) attributes each
        row's device time to the originating worker process — the
        cross-process batch case; 5-tuples (in-process) skip it."""
        cls_name, method, rows, tick_s, key_labels = payload[:5]
        self.charges += 1
        row = self.device.get((cls_name, method))
        if row is not None:
            row[0] += 1
            row[1] += rows
            row[2] += rows * tick_s
        elif len(self.device) < _MAX_ROWS:
            self.device[(cls_name, method)] = [1, rows, rows * tick_s]
        else:
            self.row_overflow += 1
        if key_labels:
            share = tick_s  # each row occupied the whole tick's wall
            for label in key_labels:
                self._charge_key(label, share, baggage=False)
        if len(payload) > 5 and payload[5]:
            for origin in payload[5]:
                if origin is None:
                    continue
                prow = self.procs.get(origin)
                if prow is not None:
                    prow[0] += 1
                    prow[1] += tick_s
                elif len(self.procs) < _MAX_ROWS:
                    self.procs[origin] = [1, tick_s]
                else:
                    self.row_overflow += 1

    def charge_wire(self, route: str, rx: int = 0, tx: int = 0) -> None:
        """Bytes moved on one route (peer endpoint / client address /
        ingress shard), charged where the sizes were already measured."""
        self.charges += 1
        row = self.wire.get(route)
        if row is not None:
            row[0] += rx
            row[1] += tx
        elif len(self.wire) < _MAX_ROWS:
            self.wire[route] = [rx, tx]
        else:
            self.row_overflow += 1

    def charge_stream(self, namespace: str, delivered: int) -> None:
        """One device-stream delivery round (streams/device.py pump)."""
        self.charges += 1
        self.streams[namespace] = \
            self.streams.get(namespace, 0) + delivered

    # -- read side ------------------------------------------------------
    def total_turn_seconds(self) -> float:
        return sum(r[1] for r in self.turns.values())

    def total_queue_seconds(self) -> float:
        return sum(r[2] for r in self.turns.values())

    def total_row_seconds(self) -> float:
        return sum(r[2] for r in self.device.values())

    def total_wire(self) -> tuple[int, int]:
        rx = sum(r[0] for r in self.wire.values())
        tx = sum(r[1] for r in self.wire.values())
        return rx, tx

    def top_burners(self, k: int = 5) -> list[dict]:
        """The window's heaviest keys, tenant-annotated — what an SLO
        breach attaches to its flight snapshot and what ``ctl_slo``
        names in the drill-down."""
        out = []
        for label, seconds, err in self.keys.top(k):
            out.append({"key": label, "seconds": round(seconds, 6),
                        "err": round(err, 6),
                        "tenant": self._tenant(label, baggage=False)})
        return out

    def register_gauges(self, stats) -> None:
        """Surface ``ledger.*`` on the registry. Gauge callables are
        evaluated only at snapshot time (Prometheus/OTLP/ctl_metrics
        pull), so exposure costs the hot path nothing."""
        stats.register_gauge(LEDGER_STATS["turn_seconds"],
                             self.total_turn_seconds)
        stats.register_gauge(LEDGER_STATS["queue_seconds"],
                             self.total_queue_seconds)
        stats.register_gauge(LEDGER_STATS["row_seconds"],
                             self.total_row_seconds)
        stats.register_gauge(LEDGER_STATS["wire_rx"],
                             lambda: self.total_wire()[0])
        stats.register_gauge(LEDGER_STATS["wire_tx"],
                             lambda: self.total_wire()[1])
        stats.register_gauge(LEDGER_STATS["stream_deliveries"],
                             lambda: sum(self.streams.values()))
        stats.register_gauge(LEDGER_STATS["charges"], lambda: self.charges)
        stats.register_gauge(LEDGER_STATS["tracked_keys"],
                             lambda: len(self.keys.counts))
        stats.register_gauge(LEDGER_STATS["key_overflow"],
                             lambda: self.keys.overflow)

    def snapshot(self, k: int | None = None) -> dict:
        """Wire-safe dict (tuple row keys joined with '.') — what
        ``ctl_ledger`` returns and ``merge`` consumes."""
        k = self.top_k if k is None else int(k)
        return {
            "turns": {f"{i}.{m}": list(r)
                      for (i, m), r in self.turns.items()},
            "device": {f"{c}.{m}": list(r)
                       for (c, m), r in self.device.items()},
            "row_overflow": self.row_overflow,
            "wire": {route: list(r) for route, r in self.wire.items()},
            "procs": {origin: list(r)
                      for origin, r in self.procs.items()},
            "streams": dict(self.streams),
            "keys": self.keys.snapshot(),
            "tenants": self.tenants.snapshot(),
            "top_burners": self.top_burners(k),
            "charges": self.charges,
        }

    @staticmethod
    def merge(snapshots: Iterable[dict]) -> dict:
        """Cluster fold of per-silo snapshots: exact tables sum, the
        sketches merge deterministically (flat fold — silo count and
        merge order cannot change the answer), and the worst burner /
        worst tenant are named from the merged ranking."""
        snapshots = [s for s in snapshots if s]
        turns: dict[str, list[float]] = {}
        device: dict[str, list[float]] = {}
        wire: dict[str, list[int]] = {}
        procs: dict[str, list[float]] = {}
        streams: dict[str, int] = {}
        row_overflow = 0
        charges = 0
        for s in snapshots:
            row_overflow += int(s.get("row_overflow", 0))
            charges += int(s.get("charges", 0))
            for name, row in s.get("turns", {}).items():
                acc = turns.setdefault(name, [0, 0.0, 0.0])
                for i in range(3):
                    acc[i] += row[i]
            for name, row in s.get("device", {}).items():
                acc = device.setdefault(name, [0, 0, 0.0])
                for i in range(3):
                    acc[i] += row[i]
            for route, row in s.get("wire", {}).items():
                acc = wire.setdefault(route, [0, 0])
                acc[0] += row[0]
                acc[1] += row[1]
            for origin, row in s.get("procs", {}).items():
                acc = procs.setdefault(origin, [0, 0.0])
                acc[0] += row[0]
                acc[1] += row[1]
            for ns, n in s.get("streams", {}).items():
                streams[ns] = streams.get(ns, 0) + n
        keys = SpaceSavingSketch.merge(
            [s.get("keys", {}) for s in snapshots])
        tenants = SpaceSavingSketch.merge(
            [s.get("tenants", {}) for s in snapshots])
        out = {
            "turns": turns, "device": device, "wire": wire,
            "procs": procs,
            "streams": streams, "row_overflow": row_overflow,
            "charges": charges, "keys": keys, "tenants": tenants,
            "worst_burner": None, "worst_tenant": None,
        }
        kc = keys.get("counts", {})
        if kc:
            label, (count, err) = min(
                kc.items(), key=lambda kv: (-kv[1][0], kv[0]))
            out["worst_burner"] = {"key": label,
                                   "seconds": round(count, 6),
                                   "err": round(err, 6)}
        tc = tenants.get("counts", {})
        if tc:
            tenant, (count, err) = min(
                tc.items(), key=lambda kv: (-kv[1][0], kv[0]))
            out["worst_tenant"] = {"tenant": tenant,
                                   "seconds": round(count, 6),
                                   "err": round(err, 6)}
        return out
