"""Chrome-trace / Perfetto export for distributed traces.

Turns the spans collected by :mod:`orleans_tpu.observability.tracing`
— typically merged from every silo of a cluster plus the client — into
one Chrome Trace Event Format file (the JSON object form with a
``traceEvents`` array) loadable in ``ui.perfetto.dev`` or
``chrome://tracing``. Each silo/client becomes a "process" row; each
trace becomes a "thread" within it, so one request's client invoke →
network → queue wait → turn execution reads left-to-right across the
process rows it touched. Span attrs (queue_s/exec_s, forward counts,
migration outcomes) land in ``args`` for the selection panel.

Device-side XLA kernel timelines come from ``jax.profiler`` capture
(:mod:`orleans_tpu.observability.profiling`); the dispatch engine opens a
``TraceAnnotation`` per tick named like the logical tick span, so the two
captures correlate by name when viewed together.
"""

from __future__ import annotations

import json

__all__ = ["chrome_trace_events", "write_chrome_trace"]


def chrome_trace_events(spans) -> list[dict]:
    """Convert span dicts (``Span.to_dict`` form) into Chrome trace
    events: one complete ("ph": "X") event per span plus process/thread
    naming metadata. Timestamps are microseconds relative to the earliest
    span so the timeline starts at zero."""
    dicts = [s if isinstance(s, dict) else s.to_dict() for s in spans]
    if not dicts:
        return []
    t0 = min(s["start"] for s in dicts)
    pids: dict[str, int] = {}
    tids: dict[tuple[int, int], int] = {}
    events: list[dict] = []
    for s in dicts:
        silo = s.get("silo") or "?"
        pid = pids.get(silo)
        if pid is None:
            pid = pids[silo] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": silo}})
        tkey = (pid, s["trace_id"])
        tid = tids.get(tkey)
        if tid is None:
            tid = tids[tkey] = len(tids) + 1
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid,
                           "args": {"name": f"trace {s['trace_id']:016x}"}})
        args = dict(s.get("attrs") or {})
        args["trace_id"] = f"{s['trace_id']:016x}"
        args["span_id"] = f"{s['span_id']:016x}"
        if s.get("parent_id"):
            args["parent_id"] = f"{s['parent_id']:016x}"
        events.append({
            "name": s["name"], "cat": s["kind"], "ph": "X",
            "ts": (s["start"] - t0) * 1e6,
            # Perfetto drops true-zero slices; clamp to 1ns so every span
            # stays visible/selectable
            "dur": max(s["duration"], 1e-9) * 1e6,
            "pid": pid, "tid": tid, "args": args,
        })
    return events


def write_chrome_trace(path: str, spans) -> str:
    """Write spans as a Chrome-trace JSON file; returns ``path``.

    One-liner for a test cluster::

        cluster.export_trace("/tmp/trace.json")   # → ui.perfetto.dev
    """
    payload = {"traceEvents": chrome_trace_events(spans),
               "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(payload, f)
    return path
