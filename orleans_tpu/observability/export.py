"""Chrome-trace / Perfetto export + streaming OTLP sink.

Turns the spans collected by :mod:`orleans_tpu.observability.tracing`
— typically merged from every silo of a cluster plus the client — into
one Chrome Trace Event Format file (the JSON object form with a
``traceEvents`` array) loadable in ``ui.perfetto.dev`` or
``chrome://tracing``. Each silo/client becomes a "process" row; each
trace becomes a "thread" within it, so one request's client invoke →
network → queue wait → turn execution reads left-to-right across the
process rows it touched. Span attrs (queue_s/exec_s, forward counts,
migration outcomes) land in ``args`` for the selection panel.

:class:`OtlpSink` is the live counterpart: it streams finished/retained
spans as OTLP/HTTP JSON (the `opentelemetry-proto` JSON mapping over
plain ``urllib`` — no exporter dependency) to a collector endpoint in
bounded batches with retry/backoff, so traces land in Jaeger/Tempo/any
OTel collector instead of per-test Chrome files. An unreachable
collector degrades to counted drops; it can never stall or break the
runtime that feeds it.

Device-side XLA kernel timelines come from ``jax.profiler`` capture
(:mod:`orleans_tpu.observability.profiling`); the dispatch engine opens a
``TraceAnnotation`` per tick named like the logical tick span, so the two
captures correlate by name when viewed together.
"""

from __future__ import annotations

import asyncio
import importlib.util
import json
import logging
import struct
import urllib.error
import urllib.request
from collections import deque

__all__ = ["chrome_trace_events", "write_chrome_trace",
           "OtlpSink", "OtlpMetricsSink", "spans_to_otlp",
           "snapshots_to_otlp_metrics", "otlp_trace_protobuf",
           "otlp_metrics_protobuf"]

log = logging.getLogger("orleans.export")

# The binary OTLP encoding is OPT-IN (encoding="protobuf") and gated on
# the collector-side schema actually being present in the environment:
# the wire bytes below are hand-assembled (varint + length-delimited
# framing over the same dicts the JSON mapping ships — no generated
# stubs, no import of the package itself), but advertising
# application/x-protobuf only makes sense where the OTel proto toolchain
# exists, and the gate keeps JSON the universal default elsewhere.
_HAS_PROTOBUF = importlib.util.find_spec("google.protobuf") is not None


def chrome_trace_events(spans, loop_profiles: dict | None = None
                        ) -> list[dict]:
    """Convert span dicts (``Span.to_dict`` form) into Chrome trace
    events: one complete ("ph": "X") event per span plus process/thread
    naming metadata. Timestamps are microseconds relative to the earliest
    span so the timeline starts at zero.

    ``loop_profiles``: optional ``{silo_name: [occupancy slices]}`` (the
    :meth:`LoopProfiler.profile` ``windows`` lists) rendered as Perfetto
    COUNTER tracks ("ph": "C") beside the span rows — per-category loop
    occupancy shares sampled once per window, on the same zeroed
    timeline, so a span's latency lines up with what occupied the loop
    around it — plus a per-silo "slow callbacks" flame row: each
    window's top-K slowest-callback records as complete spans (labels,
    categories, and placement exact — the profiler stamps each record's
    start offset within its window; offset-less legacy records fall
    back to end-to-end cursor placement from the window start). Span
    links ride into ``args`` (``links``) for the selection panel."""
    dicts = [s if isinstance(s, dict) else s.to_dict() for s in spans]
    starts = [s["start"] for s in dicts]
    for slices in (loop_profiles or {}).values():
        starts.extend(sl["ts"] - sl.get("wall_s", 0.0) for sl in slices)
    if not starts:
        # no spans and no finalized occupancy slices (e.g. a silo too
        # young for its first profiling window) — nothing to render
        return []
    t0 = min(starts)
    pids: dict[str, int] = {}
    tids: dict[tuple[int, int], int] = {}
    events: list[dict] = []
    for s in dicts:
        silo = s.get("silo") or "?"
        pid = pids.get(silo)
        if pid is None:
            pid = pids[silo] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": silo}})
        tkey = (pid, s["trace_id"])
        tid = tids.get(tkey)
        if tid is None:
            tid = tids[tkey] = len(tids) + 1
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid,
                           "args": {"name": f"trace {s['trace_id']:016x}"}})
        args = dict(s.get("attrs") or {})
        args["trace_id"] = f"{s['trace_id']:016x}"
        args["span_id"] = f"{s['span_id']:016x}"
        if s.get("parent_id"):
            args["parent_id"] = f"{s['parent_id']:016x}"
        if s.get("links"):
            args["links"] = [f"{int(lt):016x}/{int(ls):016x}"
                             for lt, ls in s["links"]]
        events.append({
            "name": s["name"], "cat": s["kind"], "ph": "X",
            "ts": (s["start"] - t0) * 1e6,
            # Perfetto drops true-zero slices; clamp to 1ns so every span
            # stays visible/selectable
            "dur": max(s["duration"], 1e-9) * 1e6,
            "pid": pid, "tid": tid, "args": args,
        })
    for silo, slices in (loop_profiles or {}).items():
        pid = pids.get(silo)
        if pid is None:
            pid = pids[silo] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": silo}})
        slow_tid = None
        cursor = float("-inf")  # monotone across windows: spilled
        # records must not overlap the NEXT window's records either
        for sl in slices:
            shares = sl.get("shares") or {}
            if shares:
                # one counter sample per occupancy window, at the window
                # END (when the slice was cut); Perfetto stacks the args
                events.append({
                    "ph": "C", "name": "loop occupancy", "pid": pid,
                    "tid": 0,
                    "ts": (sl["ts"] - t0) * 1e6,
                    "args": {k: v for k, v in sorted(shares.items())},
                })
            top = sl.get("top") or ()
            if not top:
                continue
            if slow_tid is None:
                # the flame row: the window's top-K slowest callbacks as
                # real spans beside the occupancy counter track, so a
                # breach/anomaly snapshot renders as "what the loop was
                # running" instead of an opaque record list
                slow_tid = len(tids) + 1
                tids[(pid, -1)] = slow_tid
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pid, "tid": slow_tid,
                               "args": {"name": "slow callbacks"}})
            wall = sl.get("wall_s", 0.0)
            win_start = sl["ts"] - wall
            cursor = max(cursor, win_start)
            for rec in top:
                dur = rec.get("seconds", 0.0)
                off = rec.get("offset")
                if off is not None:
                    # exact placement: the profiler stamps each record's
                    # start offset within its window (hotloop.c / the
                    # Python reference), so the record sits where the
                    # callback actually ran — no cursor approximation.
                    # Exact records cannot overlap (callbacks are
                    # sequential on one loop); the cursor still advances
                    # past them so any offset-less legacy record in the
                    # same stream stays non-overlapping.
                    start = win_start + off
                    cursor = max(cursor, start + dur)
                else:
                    # legacy records carry duration + window only — lay
                    # them end-to-end from the window start (placement
                    # approximation; durations and the owning window are
                    # exact). When durations sum past the window end,
                    # records SPILL past the boundary rather than wrap —
                    # and the cursor stays monotone into the next window
                    # — because overlapping same-tid complete events
                    # would render as bogus nesting
                    start = cursor
                    cursor += dur
                events.append({
                    "name": rec.get("label") or "?",
                    "cat": rec.get("category", "other"),
                    "ph": "X",
                    "ts": (start - t0) * 1e6,
                    "dur": max(dur, 1e-9) * 1e6,
                    "pid": pid, "tid": slow_tid,
                    "args": {"category": rec.get("category"),
                             "window_ts": sl["ts"]},
                })
    return events


def write_chrome_trace(path: str, spans,
                       loop_profiles: dict | None = None) -> str:
    """Write spans as a Chrome-trace JSON file; returns ``path``.
    ``loop_profiles`` adds per-silo loop-occupancy counter tracks
    (``{silo: profile["windows"]}``) beside the span rows.

    One-liner for a test cluster::

        cluster.export_trace("/tmp/trace.json")   # → ui.perfetto.dev
    """
    payload = {"traceEvents": chrome_trace_events(spans, loop_profiles),
               "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


# ---------------------------------------------------------------------------
# OTLP/HTTP streaming sink
# ---------------------------------------------------------------------------

# our span kinds → OTLP SpanKind enum (opentelemetry-proto trace.proto):
# 1=INTERNAL, 2=SERVER, 3=CLIENT
_OTLP_KIND = {"client": 3, "directory": 3, "server": 2}


def _otlp_value(v) -> dict:
    """One attribute value in the OTLP JSON AnyValue encoding."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # proto-JSON carries int64 as string
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _otlp_attrs(attrs: dict) -> list[dict]:
    return [{"key": k, "value": _otlp_value(v)} for k, v in attrs.items()]


def spans_to_otlp(span_dicts, service_name: str = "orleans_tpu") -> dict:
    """Convert ``Span.to_dict`` forms into one OTLP/HTTP JSON
    ``ExportTraceServiceRequest``. Our 63-bit ids zero-pad into OTLP's
    128-bit trace / 64-bit span hex ids; ``error`` attrs map to status
    ERROR; span events carry through as OTLP span events. The silo name
    rides per span (``orleans.silo``) because one batch can merge legs
    pulled from several silos, while the resource names the exporting
    process."""
    out_spans = []
    for s in span_dicts:
        attrs = dict(s.get("attrs") or {})
        err = attrs.pop("error", None)
        span = {
            "traceId": f"{s['trace_id']:032x}",
            "spanId": f"{s['span_id']:016x}",
            "name": s["name"],
            "kind": _OTLP_KIND.get(s["kind"], 1),
            "startTimeUnixNano": str(int(s["start"] * 1e9)),
            "endTimeUnixNano": str(
                int((s["start"] + s.get("duration", 0.0)) * 1e9)),
            "attributes": _otlp_attrs(attrs) + [
                {"key": "orleans.silo",
                 "value": {"stringValue": s.get("silo") or "?"}},
                {"key": "orleans.kind",
                 "value": {"stringValue": s["kind"]}},
            ],
            "status": ({"code": 2, "message": str(err)}
                       if err is not None else {}),
        }
        if s.get("parent_id"):
            span["parentSpanId"] = f"{s['parent_id']:016x}"
        links = s.get("links")
        if links:
            # span links (timer/reminder/stream arming context): OTLP
            # carries causality to the arming trace without merging them
            span["links"] = [{"traceId": f"{int(lt):032x}",
                              "spanId": f"{int(ls):016x}"}
                             for lt, ls in links]
        events = s.get("events")
        if events:
            span["events"] = [
                {"timeUnixNano": str(int(ts * 1e9)), "name": name,
                 "attributes": _otlp_attrs(ev_attrs or {})}
                for name, ts, ev_attrs in events]
        out_spans.append(span)
    return {"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name",
             "value": {"stringValue": service_name}}]},
        "scopeSpans": [{
            "scope": {"name": "orleans_tpu.observability.tracing"},
            "spans": out_spans,
        }],
    }]}


# ---------------------------------------------------------------------------
# OTLP protobuf wire encoding (opt-in; encoding="protobuf")
# ---------------------------------------------------------------------------
# Hand-assembled protobuf wire format over the SAME dicts the JSON
# mapping produces (spans_to_otlp / snapshots_to_otlp_metrics output):
# proto-JSON field names map 1:1 onto opentelemetry-proto field numbers,
# so one canonical builder feeds both encodings and they cannot drift.
# Only the shapes we emit are encoded (string/bool/int/double attrs,
# spans with events/links/status, gauge/sum/histogram metrics).

def _pb_varint(n: int) -> bytes:
    n &= 0xFFFFFFFFFFFFFFFF  # two's-complement int64, like the proto wire
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _pb_key(field: int, wire: int) -> bytes:
    return _pb_varint((field << 3) | wire)


def _pb_len(field: int, payload: bytes) -> bytes:
    return _pb_key(field, 2) + _pb_varint(len(payload)) + payload


def _pb_str(field: int, s) -> bytes:
    return _pb_len(field, s.encode() if isinstance(s, str) else bytes(s))


def _pb_u64(field: int, n) -> bytes:
    return _pb_key(field, 0) + _pb_varint(int(n))


def _pb_fixed64(field: int, n) -> bytes:
    return _pb_key(field, 1) + struct.pack("<Q",
                                           int(n) & 0xFFFFFFFFFFFFFFFF)


def _pb_sfixed64(field: int, n) -> bytes:
    return _pb_key(field, 1) + struct.pack("<q", int(n))


def _pb_double(field: int, v) -> bytes:
    return _pb_key(field, 1) + struct.pack("<d", float(v))


def _pb_anyvalue(v: dict) -> bytes:
    if "stringValue" in v:
        return _pb_str(1, v["stringValue"])
    if "boolValue" in v:
        return _pb_u64(2, 1 if v["boolValue"] else 0)
    if "intValue" in v:
        return _pb_u64(3, int(v["intValue"]))
    if "doubleValue" in v:
        return _pb_double(4, v["doubleValue"])
    return _pb_str(1, str(v))


def _pb_attrs(field: int, attrs) -> bytes:
    return b"".join(
        _pb_len(field, _pb_str(1, kv["key"]) +
                _pb_len(2, _pb_anyvalue(kv["value"])))
        for kv in attrs or ())


def _pb_span(s: dict) -> bytes:
    out = _pb_str(1, bytes.fromhex(s["traceId"]))
    out += _pb_str(2, bytes.fromhex(s["spanId"]))
    if s.get("parentSpanId"):
        out += _pb_str(4, bytes.fromhex(s["parentSpanId"]))
    out += _pb_str(5, s["name"])
    out += _pb_u64(6, s.get("kind", 1))
    out += _pb_fixed64(7, int(s["startTimeUnixNano"]))
    out += _pb_fixed64(8, int(s["endTimeUnixNano"]))
    out += _pb_attrs(9, s.get("attributes"))
    for ev in s.get("events") or ():
        out += _pb_len(11, _pb_fixed64(1, int(ev["timeUnixNano"])) +
                       _pb_str(2, ev["name"]) +
                       _pb_attrs(3, ev.get("attributes")))
    for ln in s.get("links") or ():
        out += _pb_len(13, _pb_str(1, bytes.fromhex(ln["traceId"])) +
                       _pb_str(2, bytes.fromhex(ln["spanId"])))
    status = s.get("status")
    if status:
        body = b""
        if status.get("message"):
            body += _pb_str(2, status["message"])
        if status.get("code"):
            body += _pb_u64(3, status["code"])
        out += _pb_len(15, body)
    return out


def otlp_trace_protobuf(req: dict) -> bytes:
    """An ``ExportTraceServiceRequest`` JSON-mapping dict
    (:func:`spans_to_otlp` output) as protobuf wire bytes."""
    out = b""
    for rs in req.get("resourceSpans", ()):
        body = _pb_len(1, _pb_attrs(
            1, rs.get("resource", {}).get("attributes")))
        for ss in rs.get("scopeSpans", ()):
            sbody = _pb_len(1, _pb_str(1, ss.get("scope",
                                                 {}).get("name", "")))
            for sp in ss.get("spans", ()):
                sbody += _pb_len(2, _pb_span(sp))
            body += _pb_len(2, sbody)
        out += _pb_len(1, body)
    return out


def _pb_number_point(dp: dict) -> bytes:
    out = _pb_fixed64(3, int(dp["timeUnixNano"]))
    if "asDouble" in dp:
        out += _pb_double(4, dp["asDouble"])
    if "asInt" in dp:
        out += _pb_sfixed64(6, int(dp["asInt"]))
    out += _pb_attrs(7, dp.get("attributes"))
    return out


def _pb_hist_point(dp: dict) -> bytes:
    out = _pb_fixed64(3, int(dp["timeUnixNano"]))
    out += _pb_fixed64(4, int(dp["count"]))
    out += _pb_double(5, dp.get("sum", 0.0))
    counts = dp.get("bucketCounts") or ()
    if counts:  # packed repeated fixed64
        out += _pb_len(6, b"".join(struct.pack("<Q", int(c))
                                   for c in counts))
    bounds = dp.get("explicitBounds") or ()
    if bounds:  # packed repeated double
        out += _pb_len(7, b"".join(struct.pack("<d", float(b))
                                   for b in bounds))
    out += _pb_attrs(9, dp.get("attributes"))
    return out


def _pb_metric(m: dict) -> bytes:
    out = _pb_str(1, m["name"])
    if "gauge" in m:
        out += _pb_len(5, b"".join(
            _pb_len(1, _pb_number_point(dp))
            for dp in m["gauge"]["dataPoints"]))
    elif "sum" in m:
        s = m["sum"]
        body = b"".join(_pb_len(1, _pb_number_point(dp))
                        for dp in s["dataPoints"])
        body += _pb_u64(2, s.get("aggregationTemporality", 2))
        body += _pb_u64(3, 1 if s.get("isMonotonic") else 0)
        out += _pb_len(7, body)
    elif "histogram" in m:
        h = m["histogram"]
        body = b"".join(_pb_len(1, _pb_hist_point(dp))
                        for dp in h["dataPoints"])
        body += _pb_u64(2, h.get("aggregationTemporality", 2))
        out += _pb_len(9, body)
    return out


def otlp_metrics_protobuf(req: dict) -> bytes:
    """An ``ExportMetricsServiceRequest`` JSON-mapping dict
    (:func:`snapshots_to_otlp_metrics` output) as protobuf wire bytes."""
    out = b""
    for rm in req.get("resourceMetrics", ()):
        body = _pb_len(1, _pb_attrs(
            1, rm.get("resource", {}).get("attributes")))
        for sm in rm.get("scopeMetrics", ()):
            sbody = _pb_len(1, _pb_str(1, sm.get("scope",
                                                 {}).get("name", "")))
            for m in sm.get("metrics", ()):
                sbody += _pb_len(2, _pb_metric(m))
            body += _pb_len(2, sbody)
        out += _pb_len(1, body)
    return out


class _OtlpHttpSink:
    """Shared OTLP/HTTP export machinery with the OTel-collector queue
    discipline: bounded buffer (overflow drops oldest + counts), batches
    of ``batch_size`` flushed every ``flush_interval`` seconds or as soon
    as a full batch accumulates, per-batch retry with exponential backoff,
    and give-up-drop when the collector stays unreachable. The POST runs
    in a thread executor so the event loop never blocks on the socket.

    Subclasses provide :meth:`_encode` mapping one batch of queued items
    to the request body — :class:`OtlpSink` ships span dicts as an
    ``ExportTraceServiceRequest``, :class:`OtlpMetricsSink` ships stats
    snapshots as an ``ExportMetricsServiceRequest``. Everything else
    (queue bounds, flusher task, retry/backoff, teardown fast-drop,
    counters) is identical by construction."""

    def __init__(self, endpoint: str, *, service_name: str = "orleans_tpu",
                 batch_size: int = 64, flush_interval: float = 0.5,
                 max_queue: int = 2048, max_retries: int = 2,
                 retry_backoff: float = 0.05, timeout: float = 2.0,
                 encoding: str = "json"):
        if encoding not in ("json", "protobuf"):
            raise ValueError(f"OTLP encoding must be 'json' or 'protobuf', "
                             f"got {encoding!r}")
        if encoding == "protobuf" and not _HAS_PROTOBUF:
            # degrade, don't die: the binary encoding is an optimization,
            # and a silo must come up identically in a slimmer image
            log.warning("OTLP protobuf encoding requested but "
                        "google.protobuf is not importable; using JSON")
            encoding = "json"
        self.encoding = encoding
        self.content_type = ("application/x-protobuf"
                             if encoding == "protobuf"
                             else "application/json")
        self.endpoint = endpoint
        self.service_name = service_name
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self.max_queue = max_queue
        self.max_retries = max_retries
        self.retry_backoff = retry_backoff
        self.timeout = timeout
        self._q: deque[dict] = deque()
        self._task: asyncio.Task | None = None
        self._wake: asyncio.Event | None = None
        self._closing = False
        self.exported = 0          # spans shipped
        self.exported_batches = 0  # successful POSTs
        self.dropped = 0           # spans given up on (overflow/unreachable)
        self.retries = 0           # retry attempts (observability of flap)

    def _encode(self, batch: list[dict]) -> bytes:  # pragma: no cover
        raise NotImplementedError

    # -- producer side (called by SpanCollector, sync, hot-ish path) ------
    def offer(self, span_dicts) -> None:
        q = self._q
        for d in span_dicts:
            if len(q) >= self.max_queue:
                q.popleft()
                self.dropped += 1
            q.append(d)
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop (sync tests): spans wait for an explicit flush
        if self._task is None or self._task.done():
            self._task = loop.create_task(self._run())
        if self._wake is not None and len(q) >= self.batch_size:
            self._wake.set()

    # -- flusher -----------------------------------------------------------
    async def _run(self) -> None:
        from .profiling import mark_loop_category
        mark_loop_category("observability")  # flusher steps are our tax
        self._wake = wake = asyncio.Event()
        try:
            while self._q:
                try:
                    await asyncio.wait_for(wake.wait(), self.flush_interval)
                except asyncio.TimeoutError:
                    pass
                wake.clear()
                await self.flush()
        finally:
            self._wake = None

    async def flush(self) -> None:
        """Ship everything queued, one bounded batch at a time."""
        q = self._q
        while q:
            n = min(len(q), self.batch_size)
            batch = [q.popleft() for _ in range(n)]
            if await self._send(batch):
                self.exported += n
                self.exported_batches += 1
            else:
                self.dropped += n
                if self._closing:
                    # teardown with an unreachable collector: one failed
                    # probe is enough evidence — drop the rest instead of
                    # paying the timeout per batch (silo.stop must not
                    # hang minutes on a dead exporter)
                    self.dropped += len(q)
                    q.clear()

    async def _send(self, batch: list[dict]) -> bool:
        body = self._encode(batch)
        loop = asyncio.get_running_loop()
        attempts = 1 if self._closing else self.max_retries + 1
        for attempt in range(attempts):
            try:
                await loop.run_in_executor(None, self._post, body)
                return True
            except Exception as e:  # noqa: BLE001 — collector flap/absence
                if attempt + 1 >= attempts:
                    log.debug("OTLP export to %s failed after %d attempts: "
                              "%s", self.endpoint, attempt + 1, e)
                    return False
                self.retries += 1
                await asyncio.sleep(self.retry_backoff * (2 ** attempt))
        return False

    def _post(self, body: bytes) -> None:
        # sync on purpose: runs in the executor thread, never on the loop
        req = urllib.request.Request(
            self.endpoint, data=body,
            headers={"Content-Type": self.content_type})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            if resp.status >= 400:  # urlopen raises on most, belt+braces
                raise urllib.error.HTTPError(
                    self.endpoint, resp.status, "collector rejected batch",
                    resp.headers, None)

    async def aclose(self, flush: bool = True) -> None:
        self._closing = True  # single-attempt sends + drop-on-first-failure
        if flush and self._q:
            try:
                await self.flush()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        t = self._task
        if t is not None and not t.done():
            t.cancel()
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._task = None

    def stats(self) -> dict:
        return {"exported": self.exported,
                "export_batches": self.exported_batches,
                "export_dropped": self.dropped,
                "export_retries": self.retries,
                "queued": len(self._q)}


class OtlpSink(_OtlpHttpSink):
    """Streaming OTLP/HTTP *trace* exporter. Attach to a collector:
    ``collector.sinks.append(OtlpSink(endpoint))`` — or let the silo wire
    it from ``trace_otlp_endpoint``."""

    def _encode(self, batch: list[dict]) -> bytes:
        req = spans_to_otlp(batch, self.service_name)
        if self.encoding == "protobuf":
            return otlp_trace_protobuf(req)
        return json.dumps(req).encode()


# ---------------------------------------------------------------------------
# OTLP metrics export
# ---------------------------------------------------------------------------

def _metric_points(snapshot: dict) -> list[dict]:
    """One silo's ``StatsRegistry.snapshot()`` → OTLP metric objects.
    Counters become cumulative monotonic sums, gauges become gauges,
    histograms become OTLP histograms carrying the registry's native
    bucket bounds (so the collector sees the same quantile substrate the
    Prometheus endpoint serves)."""
    from .stats import Histogram

    ts = str(int(snapshot.get("ts", 0.0) * 1e9))
    attrs = []
    silo = snapshot.get("silo")
    if silo:
        attrs = [{"key": "orleans.silo", "value": {"stringValue": silo}}]
    metrics: list[dict] = []
    for name, v in snapshot.get("counters", {}).items():
        metrics.append({"name": name, "sum": {
            "dataPoints": [{"asInt": str(int(v)), "timeUnixNano": ts,
                            "attributes": attrs}],
            "aggregationTemporality": 2,  # CUMULATIVE
            "isMonotonic": True}})
    for name, v in snapshot.get("gauges", {}).items():
        metrics.append({"name": name, "gauge": {
            "dataPoints": [{"asDouble": float(v), "timeUnixNano": ts,
                            "attributes": attrs}]}})
    for name, snap in snapshot.get("histograms", {}).items():
        h = Histogram.from_snapshot(snap)
        # explicitBounds excludes the terminal +Inf bucket (OTLP carries
        # len(bounds)+1 bucketCounts)
        bounds = [b for b in h.bounds if b != float("inf")]
        metrics.append({"name": name, "histogram": {
            "dataPoints": [{"timeUnixNano": ts, "attributes": attrs,
                            "count": str(h.total), "sum": h.sum,
                            "bucketCounts": [str(c) for c in h.counts],
                            "explicitBounds": bounds}],
            "aggregationTemporality": 2}})
    return metrics


def snapshots_to_otlp_metrics(snapshots,
                              service_name: str = "orleans_tpu") -> dict:
    """Convert stats snapshots (``StatsRegistry.snapshot()`` dicts, each
    optionally carrying a ``silo`` name) into one OTLP/HTTP JSON
    ``ExportMetricsServiceRequest``. The silo rides per data point
    (``orleans.silo``) because one batch can merge several silos'
    snapshots, while the resource names the exporting process — the same
    split :func:`spans_to_otlp` uses."""
    metrics: list[dict] = []
    for snap in snapshots:
        metrics.extend(_metric_points(snap))
    return {"resourceMetrics": [{
        "resource": {"attributes": [
            {"key": "service.name",
             "value": {"stringValue": service_name}}]},
        "scopeMetrics": [{
            "scope": {"name": "orleans_tpu.observability.metrics"},
            "metrics": metrics,
        }],
    }]}


class OtlpMetricsSink(_OtlpHttpSink):
    """Streaming OTLP/HTTP *metrics* exporter: queued items are full
    registry snapshots (the MetricsSampler offers one per push period),
    so batches stay small — same bounded-queue/retry/drop discipline as
    the span sink, tuned for snapshot-sized payloads."""

    def __init__(self, endpoint: str, *, service_name: str = "orleans_tpu",
                 batch_size: int = 4, flush_interval: float = 1.0,
                 max_queue: int = 64, max_retries: int = 2,
                 retry_backoff: float = 0.05, timeout: float = 2.0,
                 encoding: str = "json"):
        super().__init__(endpoint, service_name=service_name,
                         batch_size=batch_size,
                         flush_interval=flush_interval,
                         max_queue=max_queue, max_retries=max_retries,
                         retry_backoff=retry_backoff, timeout=timeout,
                         encoding=encoding)

    def _encode(self, batch: list[dict]) -> bytes:
        req = snapshots_to_otlp_metrics(batch, self.service_name)
        if self.encoding == "protobuf":
            return otlp_metrics_protobuf(req)
        return json.dumps(req).encode()
