"""Telemetry consumer fan-out + periodic statistics dump + watchdog.

Re-design of /root/reference/src/Orleans.Core/Telemetry/ (ITelemetryConsumer
family, TelemetryManager.cs), Core/Statistics/LogStatistics.cs:11 (periodic
registry dump), and Silo/Watchdog.cs:10 (health tick :63-104 — detects
event-loop stalls the way the reference detects GC/thread stalls).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ..runtime.silo import Silo

log = logging.getLogger("orleans.telemetry")

__all__ = ["TelemetryConsumer", "LoggingTelemetryConsumer",
           "FileTelemetryConsumer", "TelemetryManager", "Watchdog",
           "add_telemetry"]


class TelemetryConsumer:
    """Sink contract (ITelemetryConsumer): receives metric snapshots and
    tracked events."""

    def record_snapshot(self, silo_name: str, snapshot: dict) -> None:
        raise NotImplementedError

    def track_event(self, name: str, properties: dict) -> None:  # noqa: B027
        pass

    def close(self) -> None:  # noqa: B027
        pass


class LoggingTelemetryConsumer(TelemetryConsumer):
    """Dumps snapshots to the logger (the LogStatistics default)."""

    def record_snapshot(self, silo_name, snapshot) -> None:
        log.info("stats[%s]: %d counters, %d histograms", silo_name,
                 len(snapshot["counters"]), len(snapshot["histograms"]))

    def track_event(self, name, properties) -> None:
        log.info("event[%s]: %s", name, properties)


class FileTelemetryConsumer(TelemetryConsumer):
    """JSON-lines sink (the file telemetry consumer analog)."""

    def __init__(self, path: str):
        self._f = open(path, "a")

    def record_snapshot(self, silo_name, snapshot) -> None:
        self._f.write(json.dumps({"silo": silo_name, **snapshot}) + "\n")
        self._f.flush()

    def track_event(self, name, properties) -> None:
        self._f.write(json.dumps({"event": name, **properties}) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class TelemetryManager:
    """Fan-out to registered consumers on a timer (TelemetryManager.cs)."""

    def __init__(self, silo: "Silo", period: float = 5.0):
        self.silo = silo
        self.period = period
        self.consumers: list[TelemetryConsumer] = []
        self._task: asyncio.Task | None = None

    def add_consumer(self, consumer: TelemetryConsumer) -> None:
        self.consumers.append(consumer)

    def track_event(self, name: str, **properties) -> None:
        for c in self.consumers:
            try:
                c.track_event(name, properties)
            except Exception:  # noqa: BLE001
                log.exception("telemetry consumer failed")

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        for c in self.consumers:
            c.close()

    async def _loop(self) -> None:
        from .profiling import mark_loop_category
        mark_loop_category("observability")
        while True:
            await asyncio.sleep(self.period)
            self.flush()

    def flush(self) -> None:
        snapshot = self.silo.stats.snapshot()
        for c in self.consumers:
            try:
                c.record_snapshot(self.silo.config.name, snapshot)
            except Exception:  # noqa: BLE001
                log.exception("telemetry consumer failed")


class Watchdog:
    """Event-loop health monitor (Silo/Watchdog.cs:10): measures scheduling
    lag each tick; sustained lag means a turn is hogging the loop (the
    cooperative-scheduler equivalent of a GC/thread stall)."""

    def __init__(self, silo: "Silo", period: float = 1.0,
                 lag_warning: float = 0.5):
        self.silo = silo
        self.period = period
        self.lag_warning = lag_warning
        self.last_lag = 0.0
        self.max_lag = 0.0
        self._task: asyncio.Task | None = None
        # live loop-health gauges: the histogram alone cannot answer "is
        # the loop stalled RIGHT NOW", so the management surface reads
        # these from the registry snapshot. max_lag is max-since-last-
        # snapshot: reading it resets the window, so each telemetry flush
        # reports the worst stall of its own period. Destructive read by
        # design — concurrent snapshot readers (a management poll racing
        # the telemetry flush) share one window, and whichever reads
        # first gets the stall; the loop_lag histogram keeps the full
        # record either way.
        silo.stats.register_gauge("watchdog.last_lag", lambda: self.last_lag)
        silo.stats.register_gauge("watchdog.max_lag", self._drain_max_lag)

    def _drain_max_lag(self) -> float:
        v, self.max_lag = self.max_lag, 0.0
        return v

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        from .profiling import mark_loop_category
        mark_loop_category("observability")
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(self.period)
            lag = (time.monotonic() - t0) - self.period
            self.last_lag = lag
            self.max_lag = max(self.max_lag, lag)
            self.silo.stats.observe("watchdog.loop_lag", max(lag, 0.0))
            if lag > self.lag_warning:
                self.silo.stats.increment("watchdog.lag_warnings")
                lp = getattr(self.silo, "loop_prof", None)
                if lp is not None:
                    # flight recorder: the occupancy ring at the moment
                    # of the stall IS the diagnosis the watchdog can't
                    # make alone (which category ate the loop)
                    lp.trigger("watchdog_lag", lag=round(lag, 4))
                log.warning(
                    "%s: event loop lagged %.3fs (long turn or blocked "
                    "call starving the cooperative scheduler)",
                    self.silo.silo_address, lag)


def add_telemetry(builder, *consumers, period: float = 5.0,
                  watchdog_period: float = 1.0):
    """Install telemetry fan-out + watchdog on a SiloBuilder."""

    def install(silo) -> None:
        manager = TelemetryManager(silo, period)
        for c in consumers:
            manager.add_consumer(c)
        silo.telemetry = manager
        watchdog = Watchdog(silo, watchdog_period)
        silo.watchdog = watchdog
        from ..runtime.silo import ServiceLifecycleStage
        silo.subscribe_lifecycle(ServiceLifecycleStage.RUNTIME_SERVICES,
                                 manager.start, manager.stop)
        silo.subscribe_lifecycle(ServiceLifecycleStage.RUNTIME_SERVICES,
                                 watchdog.start, watchdog.stop)

    return builder.configure(install)
