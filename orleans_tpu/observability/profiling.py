"""Profiling: the host-loop occupancy profiler + flight recorder, and the
jax.profiler device-trace wrappers.

Two lenses live here:

**Device lens** (the original thin wrapper): ``Profiler.start/stop``
captures an XLA trace (TensorBoard/Perfetto timelines), ``annotate`` /
``@traced`` bridge host sections onto it, ``StepTimer`` counts slow ticks.

**Host-loop lens** (the continuous occupancy profiler): the silo's wall
time is one event loop, and at closed-loop saturation the residual
queue-wait is loop *contention* — host turns, the device tick's
sync-materialize, the socket pump, and our own observability machinery
all time-share it. :class:`LoopProfiler` measures where that loop time
actually goes, continuously and cheaply enough to leave on:

* **Interposition** (py3.10-safe — no eager task factory, no loop
  subclass needed on a running loop): :func:`install_loop_profiler`
  shadows the loop instance's ``call_soon`` / ``call_at`` /
  ``call_soon_threadsafe`` with wrappers that time every callback the
  loop runs. ``call_later`` funnels through the patched ``call_at``;
  gaps between callbacks accrue to ``idle`` — so occupancy shares sum to
  ~1.0 of wall time by construction. Uninstall deletes the instance
  attributes, restoring the class methods (refcounted per loop: the last
  silo to stop removes the hooks; co-hosted silos share one profiler
  because occupancy is a property of the LOOP, not the silo).
* **Attribution**: each callback defaults to the category riding the
  :data:`LOOP_CATEGORY` contextvar (task steps run in the task's context,
  so one ``enter``/``mark_loop_category`` at the top of a turn/pump task
  labels every later step of that task); instrumented sites segment
  finer with :meth:`LoopProfiler.set_category` (the engine splits one
  tick callback into schedule/staging/transfer/sync slices).
* **Flight recorder**: per-window occupancy slices plus the top-K
  slowest callbacks (category + grain class/method label when the turn
  declared one) land in a bounded ring; :meth:`LoopProfiler.trigger`
  snapshots the ring on anomalies — load-shed, watchdog lag,
  queue-wait-trend breach, tail-retained traces — rate-limited per
  reason, into a bounded snapshot deque the management surface serves.

Disabled (``SiloConfig.profiling_enabled=False``, the default) nothing is
installed: the loop keeps its class methods, hot paths pay one ``None``
check per site, and the off path is structurally zero-overhead.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import functools
import inspect
import logging
import sys
import time
import weakref
from collections import deque
from typing import TYPE_CHECKING, Iterator

import jax

if TYPE_CHECKING:
    from .stats import StatsRegistry

log = logging.getLogger("orleans.profiling")

# native per-callback runner (native/hotloop.c): the same accounting as
# LoopProfiler._run_cb compiled to C (~0.2us vs ~1.3us per callback).
# None when the toolchain is unavailable or ORLEANS_TPU_NATIVE=0 — the
# pure-Python path below is the behavioural reference and the fallback.
# Linux-only: the C side stamps CLOCK_MONOTONIC, which shares a base
# with time.perf_counter ONLY on Linux — on e.g. macOS the two clocks
# diverge by cumulative system-sleep time, and the Python slow paths
# (flush/finalize/profile) compare perf_counter against C-written marks.
try:
    if sys.platform.startswith("linux"):
        from ..native import load as _load_native
        _hotloop = _load_native("_hotloop")
    else:
        _hotloop = None
except Exception:  # noqa: BLE001 — native must never break import
    _hotloop = None

__all__ = ["Profiler", "annotate", "traced", "StepTimer",
           "LoopProfiler", "LOOP_CATEGORIES", "LOOP_CATEGORY",
           "install_loop_profiler", "uninstall_loop_profiler",
           "loop_profiler", "mark_loop_category"]


# ---------------------------------------------------------------------------
# Host-loop occupancy profiler
# ---------------------------------------------------------------------------

# the named occupancy buckets loop time is attributed into. "tick_sync" is
# the distinct device-sync category — host materialize/block_until_ready,
# where asynchronously-dispatched device execution is actually paid — the
# slice the "move the tick's device sync off-loop" lever would reclaim.
LOOP_CATEGORIES = (
    "turns",          # host grain turns (dispatcher._run_turn)
    "timers",         # __timer__ tick turns + timer machinery
    "tick_schedule",  # engine tick dispatch: claiming, conflict defer,
                      # future resolution
    "tick_staging",   # pending invocations -> host staging arrays
    "tick_transfer",  # host arrays -> device operands + kernel dispatch
    "tick_sync",      # host materialize: where device execution is paid
    "pump",           # socket pump + wire decode + batched routing
    "egress",         # outbound wire: response/request encode + sender
                      # writes (per-endpoint sender tasks, gateway
                      # client-route batch writes) — the slice sharded
                      # egress (SiloConfig.egress_shards) moves onto
                      # shard loops; its main-loop share is the ISSUE-15
                      # acceptance A/B
    "client",         # client-side gateway machinery sharing the loop
                      # (GatewayClient pumps/senders/reconnector) — split
                      # out of "other" so harness cost is separately
                      # attributable from silo cost in loop_attribution
    "storage",        # storage & journal provider IO awaited on-loop
    "observability",  # sampler/tracer/exporter internals
    "other",          # unattributed callbacks
    "idle",           # the loop waiting in select()
)

# Ambient default category for the CURRENT task/callback. Task steps run
# in the task's own context, so setting this once at the top of a task
# (dispatcher turn, socket pump, sampler loop) labels every later step of
# that task without per-step work; the interposition wrapper reads it at
# each callback start.
LOOP_CATEGORY: contextvars.ContextVar[str] = contextvars.ContextVar(
    "orleans_loop_category", default="other")

# 3.12+ eager task factories: ``asyncio.tasks._eager_tasks`` is the
# interpreter's registry of tasks CURRENTLY executing their first step
# eagerly (occupied for exactly that stretch). ``enter()`` consults it
# to guard the live-slice switch (see its docstring). On interpreters
# without eager factories (py3.10/3.11) this is None and the guard is a
# single constant test — the reference environment's behavior is
# unchanged.
_EAGER_TASKS = getattr(getattr(asyncio, "tasks", None), "_eager_tasks", None)


def mark_loop_category(category: str) -> None:
    """Tag the current task so its future steps default to ``category``
    (no-op cost when no profiler is installed — it only sets a
    contextvar the wrapper would read)."""
    LOOP_CATEGORY.set(category)


def _describe_callback(cb) -> str:
    """Best-effort label for an unlabeled slow callback. Task steps name
    their coroutine; everything else falls back to the qualname."""
    owner = getattr(cb, "__self__", None)
    if owner is not None:
        get_coro = getattr(owner, "get_coro", None)
        if get_coro is not None:
            try:
                coro = get_coro()
                return getattr(coro, "__qualname__", None) or repr(coro)
            except Exception:  # noqa: BLE001 — labels are best-effort
                pass
    return getattr(cb, "__qualname__", None) or type(cb).__name__


class LoopProfiler:
    """Continuous occupancy accounting for ONE event loop.

    Single-threaded by construction (every mutation happens on the loop);
    the only cross-thread entry is the ``call_soon_threadsafe`` wrapper,
    which merely wraps the callback — timing runs loop-side.

    ``window`` seconds of attribution roll into one slice dict appended
    to ``ring`` (the flight-recorder substrate); ``snapshots`` holds
    anomaly-triggered copies of the ring. ``totals`` accumulates per
    category since install — the benchmark/management read."""

    __slots__ = ("window", "top_k", "trigger_interval", "ring",
                 "snapshots", "trigger_counts", "trigger_hooks", "totals",
                 "last_shares", "closed", "started", "_win_start",
                 "_win_cats", "_win_top", "_top_min", "_last_end",
                 "_depth", "_mark", "_cur", "_cb_label",
                 "_last_trigger")

    def __init__(self, window: float = 1.0, ring: int = 120,
                 top_k: int = 8, trigger_interval: float = 1.0,
                 max_snapshots: int = 8):
        self.window = window
        self.top_k = top_k
        self.trigger_interval = trigger_interval
        self.ring: deque[dict] = deque(maxlen=ring)
        self.snapshots: deque[dict] = deque(maxlen=max_snapshots)
        self.trigger_counts: dict[str, int] = {}
        self.trigger_hooks: list = []  # called with each new snapshot
        self.totals: dict[str, float] = {}
        self.last_shares: dict[str, float] = {}
        self.closed = False
        now = time.perf_counter()
        self.started = now
        self._win_start = now
        self._win_cats: dict[str, float] = {}
        # (duration, category, label, within-window start offset)
        self._win_top: list[tuple[float, str, str, float | None]] = []
        self._top_min = 0.0      # admission bar for the top-K record path
        self._last_end = now     # end of the previous callback (idle from)
        self._depth = 0          # >0 while inside a wrapped callback
        self._mark = now         # last attribution boundary
        self._cur = "other"      # category accruing since _mark
        self._cb_label: str | None = None
        self._last_trigger: dict[str, float] = {}

    # -- interposition side ------------------------------------------------
    def _entry(self):
        """The ONE callable every schedule reuses (scheduled with the
        real callback as its first argument — no per-callback closure)."""
        return self._run_cb

    def _wrap(self, cb):
        """Compatibility/test shim around :meth:`_entry`. The installed
        loop hooks do NOT use this — they schedule the entry callable
        with the real callback as its first argument, so the steady
        state allocates no closure per scheduled callback."""
        return functools.partial(self._entry(), cb)

    def _run_cb(self, cb, *args,
                _perf=time.perf_counter, _get_cat=LOOP_CATEGORY.get):
        """Execute one scheduled callback inside occupancy boundaries.
        This runs for EVERY callback the loop executes while profiling is
        on, so the steady state is kept flat and allocation-free: two
        clock reads, one contextvar get, two dict upserts (idle gap +
        category slice — cumulative ``totals`` are folded in once per
        window, not per callback), zero extra frames. The top-K record
        path only engages for callbacks slower than the current window's
        admission bar (``_top_min``); ``_perf``/``_get_cat`` are
        default-arg locals. A closed profiler passes straight through
        (callbacks scheduled before uninstall may still run after)."""
        if self.closed or self._depth:
            if self.closed:
                return cb(*args)
            # nested invocation (a wrapped fn called synchronously from
            # inside another): inner boundaries are a no-op
            self._depth += 1
            try:
                return cb(*args)
            finally:
                self._depth -= 1
        now = _perf()
        gap = now - self._last_end
        wc = self._win_cats
        if gap > 0.0:
            # the loop was in select() between callbacks: idle
            # (try/except: the key exists after the window's first gap)
            try:
                wc["idle"] += gap
            except KeyError:
                wc["idle"] = gap
        self._depth = 1
        self._mark = now
        self._cur = _get_cat()
        self._cb_label = None
        try:
            return cb(*args)
        finally:
            end = _perf()
            self._depth = 0
            d = end - self._mark
            if d > 0.0:
                # re-read the dict slot: robust against anything inside
                # cb ever rebinding the open window
                wc = self._win_cats
                cat = self._cur
                try:
                    wc[cat] += d
                except KeyError:
                    wc[cat] = d
            self._last_end = end
            if end - now > self._top_min:
                # top-K slow-callback record (rare by construction: the
                # bar rises to the K-th slowest as the window fills)
                self._record_top(cb, end - now, now - self._win_start)
            if end - self._win_start >= self.window:
                self._finalize_window(end)

    def _record_top(self, cb, dur: float,
                    offset: float | None = None) -> None:
        """``offset`` = the callback's START relative to the open
        window's start (stamped by the hot path — C runner or the
        Python reference — so the Perfetto flame row places each record
        exactly instead of laying durations end-to-end from the window
        start). None only from legacy callers; the exporter falls back
        to cursor placement then."""
        top = self._win_top
        top.append((dur, self._cur,
                    self._cb_label or _describe_callback(cb), offset))
        if len(top) > self.top_k:
            top.sort(key=lambda t: t[0], reverse=True)
            del top[self.top_k:]
            self._top_min = top[-1][0]

    def _accrue(self, now: float) -> None:
        d = now - self._mark
        if d > 0.0:
            cat = self._cur
            self._win_cats[cat] = self._win_cats.get(cat, 0.0) + d
        self._mark = now

    # -- attribution side (instrumented runtime sites) ---------------------
    def set_category(self, category: str, label=None, *,
                     _perf=time.perf_counter) -> None:
        """Attribute loop time from here to the next boundary to
        ``category`` (segmenting WITHIN the current callback — the engine
        splits one tick callback into staging/transfer/sync). Outside a
        wrapped callback this is a no-op: there is no loop time to
        attribute, and a stale mark must not accrue. ``label`` may be a
        string or a tuple of parts — tuples are joined with "." only if
        the callback actually lands in the top-K record (the per-turn
        hot path never pays the format). Accrual is inlined — this runs
        several times per device tick and twice per host turn."""
        if not self._depth or self.closed:
            return
        now = _perf()
        d = now - self._mark
        if d > 0.0:
            wc = self._win_cats
            cat = self._cur
            try:
                wc[cat] += d
            except KeyError:
                wc[cat] = d
        self._mark = now
        self._cur = category
        if label is not None:
            self._cb_label = label

    def enter(self, category: str, label: str | None = None):
        """Category for the current slice AND the current task's future
        steps (turn bodies suspend; their resumptions must keep the
        label). Returns a token for :meth:`exit` — token discipline
        mirrors the dispatcher's contextvar usage across one task.

        Eager-aware guarded boundary (3.12+ eager task factories): an
        eagerly-executed first step runs INSIDE the callback that
        created the task, so a live-slice switch here would bleed into
        the creator's remaining frame if the step suspends (exit only
        runs on completion, in a LATER callback). The guard consults the
        interpreter's own eager-task registry (``asyncio.tasks``'
        ``_eager_tasks``, the set a task occupies exactly while its
        first step executes eagerly): inside an eager step the live
        switch is DEFERRED — the contextvar alone labels the task's
        post-suspension steps (read at each callback start), and the
        inline stretch stays honestly booked to the creator's category,
        which is where it physically ran. On interpreters without eager
        factories (the py3.10 reference environment) the registry does
        not exist, the guard is a single module-constant None test, and
        the switch is exact as before."""
        token = LOOP_CATEGORY.set(category)
        if _EAGER_TASKS is not None and self._depth:
            try:
                t = asyncio.current_task()
            except RuntimeError:
                t = None
            if t is not None and t in _EAGER_TASKS:
                return token  # deferred: guarded eager boundary
        self.set_category(category, label)
        return token

    def exit(self, token) -> None:
        LOOP_CATEGORY.reset(token)
        self.set_category(LOOP_CATEGORY.get())

    # -- windows / flight recorder ----------------------------------------
    def _finalize_window(self, now: float) -> None:
        wall = now - self._win_start
        shares = ({k: round(v / wall, 4) for k, v in self._win_cats.items()}
                  if wall > 0 else {})
        # cumulative totals are folded once per window, not per callback
        # (the hot path touches only _win_cats)
        tot = self.totals
        for k, v in self._win_cats.items():
            tot[k] = tot.get(k, 0.0) + v
        self._win_top.sort(key=lambda t: t[0], reverse=True)
        self.ring.append({
            "ts": time.time(),
            "wall_s": round(wall, 6),
            "seconds": {k: round(v, 6) for k, v in self._win_cats.items()},
            "shares": shares,
            "top": [{"seconds": round(d, 6), "category": c,
                     "label": lb if isinstance(lb, str)
                     else ".".join(str(p) for p in lb),
                     # within-window start offset: exact flame-row
                     # placement (None only via legacy _record_top calls)
                     "offset": None if off is None else round(off, 6)}
                    for d, c, lb, off in self._win_top[:self.top_k]],
        })
        self.last_shares = shares
        self._win_cats = {}
        self._win_top = []
        self._top_min = 0.0
        self._win_start = now

    def _flush(self) -> None:
        """Force an attribution boundary so reads see everything up to
        now (reads run inside a callback — a ctl turn — so depth > 0)."""
        if self._depth and not self.closed:
            self._accrue(time.perf_counter())

    def trigger(self, reason: str, **attrs) -> dict | None:
        """Anomaly hook: snapshot the ring (plus the partial current
        window) into ``snapshots``. Rate-limited per reason so a shed
        storm yields one snapshot per ``trigger_interval``, not one per
        message; every trigger still counts."""
        self.trigger_counts[reason] = self.trigger_counts.get(reason, 0) + 1
        now = time.monotonic()
        if now - self._last_trigger.get(reason, -1e9) < self.trigger_interval:
            return None
        self._last_trigger[reason] = now
        self._flush()
        snap = {
            "reason": reason,
            "ts": time.time(),
            "attrs": attrs,
            "slices": list(self.ring),
            "current": {
                "seconds": {k: round(v, 6)
                            for k, v in self._win_cats.items()},
                "window_open_s": round(
                    time.perf_counter() - self._win_start, 6),
            },
        }
        self.snapshots.append(snap)
        for hook in self.trigger_hooks:
            try:
                hook(snap)
            except Exception:  # noqa: BLE001 — a sink must not break the loop
                log.exception("flight-recorder trigger hook failed")
        return snap

    # -- reads -------------------------------------------------------------
    def _cumulative(self) -> dict[str, float]:
        """Finalized-window totals plus the open window's accrual (the
        hot path folds into ``totals`` only at window boundaries)."""
        self._flush()
        out = dict(self.totals)
        for k, v in self._win_cats.items():
            out[k] = out.get(k, 0.0) + v
        return out

    def occupancy(self) -> dict[str, float]:
        """Cumulative per-category shares of accounted wall time
        (busy + idle); sums to ~1.0 by construction."""
        cum = self._cumulative()
        wall = sum(cum.values())
        if wall <= 0:
            return {}
        return {k: v / wall for k, v in cum.items()}

    def profile(self, windows: int = 20,
                snapshots: bool = True) -> dict:
        """The management-surface payload: cumulative seconds + shares,
        the last ``windows`` slices, and (optionally) the flight-recorder
        snapshots."""
        cum = self._cumulative()
        wall = sum(cum.values())
        out = {
            "window_s": self.window,
            "wall_s": round(wall, 6),
            "seconds": {k: round(v, 6) for k, v in cum.items()},
            "shares": {k: round(v / wall, 4)
                       for k, v in cum.items()} if wall else {},
            "windows": list(self.ring)[-windows:] if windows else [],
            "triggers": dict(self.trigger_counts),
        }
        if snapshots:
            out["snapshots"] = list(self.snapshots)
        return out


class _NativeLoopProfiler(LoopProfiler):
    """LoopProfiler whose per-callback hot path runs in C
    (native/hotloop.c). The C ``Runner`` owns the hot state — attribution
    boundary, open-window category dict, top-K admission bar, depth/
    closed flags — and every Python slow path (window finalize, trigger,
    flush, enter/exit) keeps working unchanged through the delegating
    properties installed below, which read and write the very same C
    struct members. Semantics are identical to the pure-Python parent
    (the behavioural reference, still exercised by the unit tests and
    the ``ORLEANS_TPU_NATIVE=0`` fallback)."""

    __slots__ = ("_c",)

    def __init__(self, *args, **kwargs):
        # the runner must exist BEFORE the parent __init__ writes state
        # through the delegating properties
        object.__setattr__(self, "_c", _hotloop.Runner(LOOP_CATEGORY, self))
        super().__init__(*args, **kwargs)

    def _entry(self):
        return self._c  # the Runner IS the scheduled callable

    def set_category(self, category: str, label=None) -> None:
        self._c.set_category(category, label)


def _delegate(cname: str) -> property:
    return property(lambda self, _n=cname: getattr(self._c, _n),
                    lambda self, v, _n=cname: setattr(self._c, _n, v))


for _name, _cname in (("window", "window"), ("closed", "closed"),
                      ("_win_start", "win_start"), ("_win_cats", "win_cats"),
                      ("_top_min", "top_min"), ("_last_end", "last_end"),
                      ("_depth", "depth"), ("_mark", "mark"),
                      ("_cur", "cur"), ("_cb_label", "cb_label")):
    setattr(_NativeLoopProfiler, _name, _delegate(_cname))
del _name, _cname


def _profiler_class() -> type[LoopProfiler]:
    return LoopProfiler if _hotloop is None else _NativeLoopProfiler


# one interposition per loop, refcounted: loop -> [refs, profiler,
# originals]. Weakly keyed: a loop abandoned without uninstall (a silo
# that died mid-start, a test loop dropped on the floor) must not leave
# an entry behind — id() reuse on a later loop would alias it onto the
# stale closed profiler and silently skip installing hooks.
_loop_profilers: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def loop_profiler(loop) -> LoopProfiler | None:
    """The profiler installed on ``loop``, or None."""
    ent = _loop_profilers.get(loop)
    return ent[1] if ent else None


def install_loop_profiler(loop, *, window: float = 1.0, ring: int = 120,
                          top_k: int = 8,
                          trigger_interval: float = 1.0) -> LoopProfiler:
    """Interpose occupancy accounting on ``loop`` (idempotent +
    refcounted: silos sharing a loop share ONE profiler — occupancy is a
    loop property — and the last :func:`uninstall_loop_profiler` removes
    the hooks). Instance-attribute shadowing keeps this py3.10-safe: no
    loop subclass, no task factory, works on a loop that is already
    running. ``call_later`` is covered through the patched ``call_at``;
    executor completions arrive via the patched ``call_soon_threadsafe``;
    selector IO-ready callbacks (transport ``_read_ready`` — the recv
    syscall + buffer feed that would otherwise land in the inter-callback
    gap and be booked as idle) are covered through the patched
    ``_add_reader``/``_add_writer`` and attributed to ``pump`` (in this
    runtime an FD becoming readable IS fabric/gateway socket work).

    Known tradeoff: scheduling hooks prepend the runner via C-level
    ``functools.partial`` — no Python frame per schedule, which is the
    whole overhead budget — so asyncio's callable check inspects the
    runner, not the user callback; a non-callable (e.g. a bare
    coroutine object) fails inside the Handle via the loop exception
    handler instead of raising TypeError at the buggy call site. A
    pre-validating Python wrapper would re-add the per-schedule frame
    this design exists to avoid."""
    ent = _loop_profilers.get(loop)
    if ent is not None:
        ent[0] += 1
        return ent[1]
    prof = _profiler_class()(window=window, ring=ring, top_k=top_k,
                             trigger_interval=trigger_interval)
    # the ONE entry callable every schedule reuses (the C Runner when
    # native, the bound _run_cb otherwise): scheduling it with the real
    # callback as its first argument costs no closure/partial allocation
    # per callback (the dominant interposition tax otherwise).
    # call_soon/call_soon_threadsafe prepend it via a C-level
    # functools.partial — zero Python frames on the schedule path:
    #   loop.call_soon(cb, *a, context=c)
    #     -> orig_call_soon(run_cb, cb, *a, context=c)
    # call_at needs a real wrapper (``when`` precedes the callback), and
    # timers are orders of magnitude rarer than call_soon.
    run_cb = prof._entry()
    call_soon = functools.partial(loop.call_soon, run_cb)
    call_soon_threadsafe = functools.partial(loop.call_soon_threadsafe,
                                             run_cb)

    def call_at(when, callback, *args, context=None,
                _at=loop.call_at, _run=run_cb):
        return _at(when, _run, callback, *args, context=context)

    loop.call_soon = call_soon
    loop.call_at = call_at
    loop.call_soon_threadsafe = call_soon_threadsafe
    names = ["call_soon", "call_at", "call_soon_threadsafe"]
    if hasattr(loop, "_add_reader"):
        # selector loops only (proactor has no fd readers). The Handle
        # captures its context at REGISTRATION, so registering inside a
        # context with LOOP_CATEGORY already set to "pump" labels every
        # run of the IO callback without per-run work.
        pump_ctx = contextvars.Context()
        pump_ctx.run(LOOP_CATEGORY.set, "pump")

        def _add_reader(fd, callback, *args, _orig=loop._add_reader,
                        _run=run_cb, _ctx=pump_ctx):
            return _ctx.run(_orig, fd, _run, callback, *args)

        def _add_writer(fd, callback, *args, _orig=loop._add_writer,
                        _run=run_cb, _ctx=pump_ctx):
            return _ctx.run(_orig, fd, _run, callback, *args)

        loop._add_reader = _add_reader
        loop._add_writer = _add_writer
        names += ["_add_reader", "_add_writer"]
    _loop_profilers[loop] = [1, prof, tuple(names)]
    log.info("loop profiler installed (window=%.2fs, ring=%d)", window, ring)
    return prof


def uninstall_loop_profiler(loop) -> None:
    """Drop one reference; the last removes the instance-attribute hooks
    (class methods take over again) and closes the profiler so
    already-wrapped callbacks pass straight through."""
    ent = _loop_profilers.get(loop)
    if ent is None:
        return
    ent[0] -= 1
    if ent[0] > 0:
        return
    del _loop_profilers[loop]
    _, prof, names = ent
    prof.closed = True
    for name in names:
        try:
            delattr(loop, name)
        except AttributeError:
            pass


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span on the profiler timeline (no-op cost when no trace is
    active)."""
    with jax.profiler.TraceAnnotation(name):
        yield


def traced(name: str):
    """Decorator form of :func:`annotate`. Coroutine-aware: wrapping an
    ``async def`` keeps the annotation open across the whole awaited turn
    (a naive wrapper would return the coroutine object and close the span
    before the turn ever ran). Function metadata is preserved."""
    def wrap(fn):
        if inspect.iscoroutinefunction(fn):
            @functools.wraps(fn)
            async def inner(*args, **kwargs):
                with jax.profiler.TraceAnnotation(name):
                    return await fn(*args, **kwargs)
            return inner

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with jax.profiler.TraceAnnotation(name):
                return fn(*args, **kwargs)
        return inner
    return wrap


class Profiler:
    """Start/stop XLA trace capture (jax.profiler.start_trace). One active
    capture per process; ``stop()`` is idempotent."""

    def __init__(self) -> None:
        self.active_dir: str | None = None

    def start(self, log_dir: str) -> None:
        if self.active_dir is not None:
            raise RuntimeError(f"trace already active → {self.active_dir}")
        jax.profiler.start_trace(log_dir)
        self.active_dir = log_dir
        log.info("device trace capturing → %s", log_dir)

    def stop(self) -> str | None:
        if self.active_dir is None:
            return None
        jax.profiler.stop_trace()
        out, self.active_dir = self.active_dir, None
        log.info("device trace written → %s", out)
        return out

    @contextlib.contextmanager
    def capture(self, log_dir: str) -> Iterator[None]:
        self.start(log_dir)
        try:
            yield
        finally:
            self.stop()


class StepTimer:
    """Wall-clock per named step into a stats histogram, warning on slow
    steps (the device-tier TurnWarningLengthThreshold,
    OrleansTaskScheduler.cs:26)."""

    def __init__(self, stats: "StatsRegistry", name: str,
                 warn_threshold: float = 0.2):
        self.stats = stats
        self.name = name
        self.warn_threshold = warn_threshold

    @contextlib.contextmanager
    def step(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation(self.name):
                yield
        finally:
            # record failed steps too — crashed/timed-out ticks are the
            # most important ones in the latency telemetry
            dt = time.perf_counter() - t0
            self.stats.observe(f"{self.name}.seconds", dt)
            if dt > self.warn_threshold:
                self.stats.increment(f"{self.name}.slow")
                log.warning("%s took %.3fs (threshold %.3fs)", self.name,
                            dt, self.warn_threshold)
