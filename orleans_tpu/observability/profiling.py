"""Device profiling: jax.profiler traces wired into the stats registry.

The reference's tracing story is ActivityId correlation + hot-path counters
dumped periodically (SURVEY §5 "Tracing / profiling"); its TPU equivalent
is ``jax.profiler`` traces (XLA op timelines viewable in TensorBoard/
Perfetto) plus named annotations so dispatch ticks show up as spans. The
silo keeps its counters (observability.stats); this module adds the
device-side lens:

* ``Profiler.start(log_dir)`` / ``stop()`` — capture an XLA trace of
  everything the runtime launches in between;
* ``annotate(name)`` / ``@traced(name)`` — named spans (TraceAnnotation)
  around host-side sections, e.g. one per dispatch tick, so the timeline
  correlates ticks with kernels;
* ``StepTimer`` — per-tick wall-clock into a stats histogram (the
  TurnWarningLengthThreshold analog for the device tier: slow ticks are
  counted and logged).
"""

from __future__ import annotations

import contextlib
import functools
import inspect
import logging
import time
from typing import TYPE_CHECKING, Iterator

import jax

if TYPE_CHECKING:
    from .stats import StatsRegistry

log = logging.getLogger("orleans.profiling")

__all__ = ["Profiler", "annotate", "traced", "StepTimer"]


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span on the profiler timeline (no-op cost when no trace is
    active)."""
    with jax.profiler.TraceAnnotation(name):
        yield


def traced(name: str):
    """Decorator form of :func:`annotate`. Coroutine-aware: wrapping an
    ``async def`` keeps the annotation open across the whole awaited turn
    (a naive wrapper would return the coroutine object and close the span
    before the turn ever ran). Function metadata is preserved."""
    def wrap(fn):
        if inspect.iscoroutinefunction(fn):
            @functools.wraps(fn)
            async def inner(*args, **kwargs):
                with jax.profiler.TraceAnnotation(name):
                    return await fn(*args, **kwargs)
            return inner

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with jax.profiler.TraceAnnotation(name):
                return fn(*args, **kwargs)
        return inner
    return wrap


class Profiler:
    """Start/stop XLA trace capture (jax.profiler.start_trace). One active
    capture per process; ``stop()`` is idempotent."""

    def __init__(self) -> None:
        self.active_dir: str | None = None

    def start(self, log_dir: str) -> None:
        if self.active_dir is not None:
            raise RuntimeError(f"trace already active → {self.active_dir}")
        jax.profiler.start_trace(log_dir)
        self.active_dir = log_dir
        log.info("device trace capturing → %s", log_dir)

    def stop(self) -> str | None:
        if self.active_dir is None:
            return None
        jax.profiler.stop_trace()
        out, self.active_dir = self.active_dir, None
        log.info("device trace written → %s", out)
        return out

    @contextlib.contextmanager
    def capture(self, log_dir: str) -> Iterator[None]:
        self.start(log_dir)
        try:
            yield
        finally:
            self.stop()


class StepTimer:
    """Wall-clock per named step into a stats histogram, warning on slow
    steps (the device-tier TurnWarningLengthThreshold,
    OrleansTaskScheduler.cs:26)."""

    def __init__(self, stats: "StatsRegistry", name: str,
                 warn_threshold: float = 0.2):
        self.stats = stats
        self.name = name
        self.warn_threshold = warn_threshold

    @contextlib.contextmanager
    def step(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation(self.name):
                yield
        finally:
            # record failed steps too — crashed/timed-out ticks are the
            # most important ones in the latency telemetry
            dt = time.perf_counter() - t0
            self.stats.observe(f"{self.name}.seconds", dt)
            if dt > self.warn_threshold:
                self.stats.increment(f"{self.name}.slow")
                log.warning("%s took %.3fs (threshold %.3fs)", self.name,
                            dt, self.warn_threshold)
