"""Statistics registry (L13).

Re-design of /root/reference/src/Orleans.Core/Statistics/ (CounterStatistic,
IntValueStatistic, HistogramValueStatistic, StatisticNames) — a flat named
registry of counters/gauges/histograms per silo, cheap enough for hot paths,
dumpable for the management surface and test assertions.
"""

from __future__ import annotations

import bisect
import time
from collections import deque
from typing import Callable

__all__ = ["StatsRegistry", "Histogram", "QueueWaitTrend", "CallSiteStats",
           "DISPATCH_STATS", "REBALANCE_STATS", "INGEST_STATS",
           "INGEST_STAGES", "EGRESS_STATS", "EGRESS_STAGES", "RING_STATS",
           "RING_STAGES", "SLO_STATS", "SIZE_BOUNDS", "COUNT_BOUNDS"]

# Hot-lane dispatch counter pair (runtime.hotlane): hits = calls that ran
# as frame-collapsed inline turns (including the always-interleave direct
# lane), fallbacks = calls that took the full messaging path. Exposed as
# gauges (the underlying counters are plain ints on the RuntimeClient — a
# registry increment per call was measurable in the r5 attribution); the
# hit ratio hits/(hits+fallbacks) is the bench/SLO signal.
DISPATCH_STATS = {
    "hot_hits": "dispatch.hotlane.hits",
    "hot_fallbacks": "dispatch.hotlane.fallbacks",
}

# Canonical rebalancer counter/gauge names (orleans_tpu.rebalance wires
# its per-round outcomes here; tests and the management surface read them
# by these names rather than re-deriving strings).
REBALANCE_STATS = {
    "rounds": "rebalance.rounds",                  # counter: rounds run
    "planned": "rebalance.planned",                # counter: moves planned
    "migrated": "rebalance.activations.migrated",  # counter: host moves done
    "rows_moved": "rebalance.rows.moved",          # counter: device rows
    "rolled_back": "rebalance.rolled_back",        # counter: failed+undone
    "refused": "rebalance.refused",                # counter: dest refused
    "dropped": "rebalance.dropped",                # counter: over budget
    "last_moved": "rebalance.last_round.moved",    # gauge: last round total
    "last_imbalance": "rebalance.last_round.imbalance",  # gauge: hot/mean
    # gauge: cluster-wide device-shard heat ratio (hottest silo's per-class
    # hit total / cluster mean), computed from peers' broadcast vector_hits
    # — the early-warning signal for the cross-silo row-migration follow-on
    "device_hot_ratio": "rebalance.cluster.device_hot_ratio",
}


# Canonical ingest-pipeline stage metrics (the socket→device attribution
# substrate — ROADMAP "break the ingest wall"). Stage latency histograms
# decompose one ingested message's wall time into contiguous segments
# against a single monotonic stamp carried on the envelope (the
# Message.received_at slot, wire-excluded, re-stamped at each boundary;
# every observe/re-stamp happens BEFORE the step that could consume the
# envelope — routing can synchronously run a turn and recycle the shell):
#
#   decode      wire.decode_message (native hotwire or pickle fallback);
#               stamps received_at at decode end
#   enqueue     arrival -> leaving the MessageCenter inbound queue
#               (inline routing makes this ~0; a backlogged QoS category
#               shows its queue dwell here); re-stamps before routing
#   queue_wait  hand-off -> work start. Host tier: routing + mailbox +
#               task scheduling, observed at turn start. Device tier:
#               engine enqueue -> batch start (tick scheduling +
#               conflict-deferred ticks), observed per item by the
#               OWNING silo's engine only — forwarded/rejected hops
#               never add samples
#   staging     vector batch pack (pending invocations -> host arrays)
#   transfer    host arrays -> device operands
#   tick        kernel dispatch + device execution + host materialize
#
# Host-tier turns end at queue_wait (execution is scheduler.turn_length);
# device-tier requests continue through staging/transfer/tick. Everything
# is gated on SiloConfig.metrics_enabled — one attr check when off.
INGEST_STAGES = ("decode", "enqueue", "queue_wait", "staging", "transfer",
                 "tick")

INGEST_STATS = {
    "decode": "ingest.decode.seconds",
    "decode_bytes": "ingest.decode.bytes",       # SIZE_BOUNDS histogram
    "frames": "ingest.frames",                   # counter: frames decoded
    "frame_batch": "ingest.frame_batch.size",    # COUNT_BOUNDS histogram
    "enqueue": "ingest.enqueue.seconds",
    "queue_wait": "ingest.queue_wait.seconds",
    "turns": "ingest.turns",                     # counter: host turns timed
    "staging": "ingest.staging.seconds",
    "transfer": "ingest.transfer.seconds",
    "tick": "ingest.tick.seconds",
    "messages": "ingest.messages",               # counter: device msgs ticked
}


# Canonical egress-pipeline stage metrics — the response-path twin of
# INGEST_STATS (the batched-egress pipeline: Dispatcher.send_response →
# EgressBatcher → MessageCenter.send_batch → one encode_message_batch
# write per destination). Stage latency histograms decompose the
# response leg the same way the ingest stages decompose the request leg:
#
#   build    per-flush grouping/hand-off work in EgressBatcher.flush
#            (the response-batch resolution cost itself)
#   dwell    send-queue dwell: a response entering the per-destination
#            flush accumulator -> leaving it at the batch-completion
#            flush (never spans a loop turn by construction — a growing
#            dwell means flush groups are forming across big completion
#            bursts, the batching-degree signal's latency face)
#   encode   wire encode of one outbound batch (header-prefix template +
#            pack_batch on the native build), observed per
#            encode_message_batch call by metrics-enabled egress writers.
#            Under sharded egress (SiloConfig.egress_shards) the encode
#            runs on a shard loop: it is STAMPED shard-side and
#            REPLAYED loop-side over the shard's stat ring (the PR-9/11
#            loop-confinement rule) — same series, same semantics, and
#            dwell then spans accumulator + egress ring + sender queue
#            (the whole pre-encode wait, stamped at shard encode time)
#
#   group    flush-group size (COUNT_BOUNDS histogram — the egress twin
#            of ingest frame_batch: responses per hand-off unit)
#
# Everything is gated on SiloConfig.metrics_enabled exactly like the
# ingest stages — one attr check per site when off.
EGRESS_STAGES = ("build", "dwell", "encode")

EGRESS_STATS = {
    "build": "egress.build.seconds",
    "dwell": "egress.dwell.seconds",
    "encode": "egress.encode.seconds",
    "group": "egress.flush_group.size",       # COUNT_BOUNDS histogram
    "responses": "egress.responses",          # counter: responses batched
    # counter: messages dropped at a FULL egress shard ring (bounded
    # backpressure toward a wedged peer — the only direction possible
    # for a producer that cannot pause response generation; senders
    # learn via response timeout exactly like a dead-peer send drop)
    "ring_drops": "egress.ring_drops",
}


# Canonical shm-ring stage metrics — the cross-process leg of the ingest
# decomposition (runtime.multiproc: worker SO_REUSEPORT silos feed the
# device owner over shared-memory SPSC staging rings; responses return
# over per-worker response rings). Stage histograms attribute the ring
# hop the same way INGEST_STAGES attribute the in-process pipeline:
#
#   staging_dwell   push (worker-side VectorShmClient.call_group) ->
#                   pop (owner-side WorkerSupervisor drain) of one
#                   staging-ring record, against the system-wide
#                   CLOCK_MONOTONIC stamp carried in the record.
#                   Stamped push-side in the worker process, observed
#                   pop-side on the owner's loop (the cross-PROCESS
#                   analog of the stamp-and-replay rule: the stamp is
#                   plain bytes in the ring record, the observe runs
#                   loop-confined on the consumer)
#   response_dwell  push (owner-side _flush_link) -> pop (worker-side
#                   response drain) of one response batch — the return
#                   leg, observed on the worker's loop
#   drain_batch     records drained per owner wakeup (COUNT_BOUNDS —
#                   the ring twin of ingest frame_batch: a rising batch
#                   size under load is the rings' natural coalescing)
#   group           packed-group size: vector subs per "vec" record
#                   (COUNT_BOUNDS — the cross-process batching degree)
#   hops            relay hop count per record (COUNT_BOUNDS — 1 for
#                   the direct worker->owner path today; forwarded/
#                   re-pushed records would accumulate here)
#
# Everything is gated on SiloConfig.metrics_enabled exactly like the
# ingest/egress stages — one attr check per site when off.
RING_STAGES = ("staging_dwell", "response_dwell")

RING_STATS = {
    "staging_dwell": "ring.staging.dwell.seconds",
    "response_dwell": "ring.response.dwell.seconds",
    "drain_batch": "ring.drain_batch.size",      # COUNT_BOUNDS histogram
    "group": "ring.packed_group.size",           # COUNT_BOUNDS histogram
    "hops": "ring.relay.hops",                   # COUNT_BOUNDS histogram
    "records": "ring.records",                   # counter: records drained
}


# Canonical SLO-engine metric names (observability.slo.SloMonitor writes
# these; the management surface, the Prometheus endpoint, and the
# gauntlet verdicts read them by name). Per-objective gauges are
# formatted with the objective name: ``SLO_STATS['burn_fast'] % name``.
SLO_STATS = {
    "breaches": "slo.breaches",                 # counter: breach episodes
    "evaluations": "slo.evaluations",           # counter: monitor ticks
    "breach": "slo.breach.%s",                  # counter per objective
    "burn_fast": "slo.%s.burn_fast",            # gauge: fast-window burn
    "burn_slow": "slo.%s.burn_slow",            # gauge: slow-window burn
    "budget_burned": "slo.%s.budget_burned",    # gauge: cum budget spent
    "breached": "slo.%s.breached",              # gauge: 0/1 current state
    # membership probe round-trip latency (membership.oracle observes one
    # sample per probe) — the QoS-category SLO source proving PING
    # traffic never sits behind application load or SLO evaluation
    "probe_rtt": "membership.probe.rtt.seconds",
    # host-turn failures (dispatcher._run_turn error path) — the
    # error-rate objective's bad-event counter
    "turn_errors": "turns.errors",
}


class Histogram:
    """Fixed-bucket histogram (HistogramValueStatistic). Default bounds
    are latency seconds; size/count series pass their own (SIZE_BOUNDS /
    COUNT_BOUNDS below) — non-default bounds ride along in
    :meth:`summary` so snapshots merge and expose losslessly."""

    # default bucket upper bounds in seconds
    BOUNDS = [0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
              0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, float("inf")]

    def __init__(self, bounds: list[float] | None = None) -> None:
        self.bounds = self.BOUNDS if bounds is None else list(bounds)
        self.counts = [0] * len(self.bounds)
        self.total = 0
        self.sum = 0.0
        # per-bucket OpenMetrics exemplars: bucket index -> (value,
        # trace_id, wall ts). Lazily allocated — the common untraced
        # histogram carries None and pays one attr slot
        self.exemplars: dict[int, tuple[float, int, float]] | None = None

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.total += 1
        self.sum += value

    def exemplar(self, value: float, trace_id: int) -> None:
        """Attach an OpenMetrics exemplar to the bucket ``value`` lands
        in (last-writer-wins per bucket, the standard exemplar
        discipline): the observation was made by a SAMPLED request, so a
        slow bucket on the exposition endpoint links straight into the
        tail-retained trace that filled it. Separate from observe() so
        the unsampled hot path never takes an extra argument."""
        ex = self.exemplars
        if ex is None:
            ex = self.exemplars = {}
        ex[min(bisect.bisect_left(self.bounds, value),
               len(self.counts) - 1)] = (value, trace_id, time.time())

    def percentile(self, p: float) -> float:
        """Approximate percentile from bucket bounds (upper bound of the
        bucket containing the p-quantile observation)."""
        if self.total == 0:
            return 0.0
        rank = p * self.total
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                return self.bounds[i]
        return self.bounds[-1]

    def quantile(self, q: float) -> float:
        """Arbitrary-quantile read (the exposition-friendly name for
        :meth:`percentile`; q in [0, 1])."""
        return self.percentile(q)

    def bucket_labels(self) -> list[str]:
        """Prometheus/OpenMetrics ``le`` label values, one per bucket, in
        bound order with the terminal ``+Inf`` — so the exposition endpoint
        serves this histogram without re-bucketing."""
        return [("+Inf" if b == float("inf") else f"{b:g}")
                for b in self.bounds]

    def cumulative_counts(self) -> list[int]:
        """Per-bucket counts as the cumulative form the Prometheus
        ``_bucket`` series requires (monotone, last == count)."""
        out, running = [], 0
        for c in self.counts:
            running += c
            out.append(running)
        return out

    @property
    def mean(self) -> float:
        return self.sum / self.total if self.total else 0.0

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold another histogram in — the management grain aggregates
        per-silo histograms cluster-wide with this.

        Mismatched per-instance bucket bounds (one silo created a series
        with SIZE_BOUNDS, another with the latency defaults — the
        first-creation-wins ``histogram_with`` race across silos) widen
        DETERMINISTICALLY instead of silently mis-bucketing positionally:
        each source bucket folds into the target bucket whose range
        contains the source bucket's upper bound (counts can only move
        coarser, never into a lower bucket, so merged quantiles are
        conservative upper bounds). Exemplars re-locate by their exact
        observed value either way."""
        if other.bounds == self.bounds:
            for i, c in enumerate(other.counts):
                self.counts[i] += c
        else:
            last = len(self.counts) - 1
            for b, c in zip(other.bounds, other.counts):
                if c:
                    self.counts[min(bisect.bisect_left(self.bounds, b),
                                    last)] += c
        self.total += other.total
        self.sum += other.sum
        if other.exemplars:
            for v, tid, ts in other.exemplars.values():
                mine = self.exemplars or {}
                idx = min(bisect.bisect_left(self.bounds, v),
                          len(self.counts) - 1)
                cur = mine.get(idx)
                if cur is None or ts >= cur[2]:  # newest exemplar wins
                    mine[idx] = (v, tid, ts)
                    self.exemplars = mine
        return self

    def summary(self) -> dict:
        """The snapshot form (per-bucket counts — and non-default bounds
        and exemplars — ride along so summaries merge losslessly via
        :meth:`from_snapshot`)."""
        out = {"count": self.total, "sum": self.sum, "mean": self.mean,
               "p50": self.percentile(0.5), "p95": self.percentile(0.95),
               "p99": self.percentile(0.99), "buckets": list(self.counts)}
        if self.bounds is not self.BOUNDS:
            out["bounds"] = list(self.bounds)
        if self.exemplars:
            # str keys: the snapshot is a wire/JSON form
            out["exemplars"] = {str(i): list(e)
                                for i, e in self.exemplars.items()}
        return out

    @classmethod
    def from_snapshot(cls, d: dict) -> "Histogram":
        """Rebuild from a :meth:`summary` dict (cross-silo aggregation:
        snapshots travel the wire, histogram objects do not). A bucket
        list that disagrees with its own bounds is corrupt — raise
        rather than mis-state counts against the wrong buckets."""
        h = cls(d.get("bounds"))
        counts = d.get("buckets")
        if counts:
            if len(counts) != len(h.counts):
                raise ValueError(
                    f"histogram snapshot carries {len(counts)} buckets "
                    f"for {len(h.counts)} bounds — refusing to "
                    "mis-bucket a corrupt snapshot")
            h.counts = [int(c) for c in counts]
        h.total = int(d.get("count", sum(h.counts)))
        h.sum = float(d.get("sum", 0.0))
        ex = d.get("exemplars")
        if ex:
            h.exemplars = {int(i): (float(v), int(t), float(ts))
                           for i, (v, t, ts) in ex.items()}
        return h

    def delta(self, snapshot: dict | None) -> "Histogram":
        """Interval diff: a NEW histogram holding the observations made
        since ``snapshot`` (a prior :meth:`summary` of this same series)
        was taken — the primitive burn-rate windows and attribution
        benches are built on, replacing hand-rolled snapshot subtraction.

        ``snapshot=None`` (no prior read) returns a copy of the whole
        cumulative state. Mismatched bucket bounds (the series was
        re-created with different bounds between reads, or the snapshot
        crossed silos) are safe via the same deterministic widening rule
        :meth:`merge` uses — each snapshot bucket folds into the bucket
        of THIS histogram's bounds containing its upper bound before
        subtracting, so counts never subtract positionally against the
        wrong bucket. Per-bucket differences clamp at zero (a widened
        fold can shift counts across buckets; clamping keeps the delta
        conservative rather than negative), ``count`` is the sum of the
        clamped buckets, and ``sum`` clamps at 0.0. Exemplars do not
        carry (they are last-writer point events, not interval state)."""
        bounds = None if self.bounds is self.BOUNDS else self.bounds
        out = Histogram(bounds)
        out.counts = list(self.counts)
        out.sum = self.sum
        if snapshot:
            prev = Histogram.from_snapshot(snapshot)
            if prev.bounds != self.bounds:
                # widen the snapshot's counts onto OUR bounds first
                # (merge's coarsening rule), then subtract
                folded = [0] * len(self.counts)
                last = len(folded) - 1
                for b, c in zip(prev.bounds, prev.counts):
                    if c:
                        folded[min(bisect.bisect_left(self.bounds, b),
                                   last)] += c
                prev_counts = folded
            else:
                prev_counts = prev.counts
            out.counts = [max(0, c - p)
                          for c, p in zip(out.counts, prev_counts)]
            out.sum = max(0.0, out.sum - prev.sum)
        out.total = sum(out.counts)
        return out

    def good_below(self, threshold: float) -> int:
        """Observations provably <= ``threshold`` from bucket counts:
        the sum of buckets whose upper bound does not exceed it (the
        bucket the threshold falls INSIDE is excluded — conservative,
        like merged quantiles). The SLI numerator for latency
        objectives: good = fast-enough events."""
        good = 0
        for b, c in zip(self.bounds, self.counts):
            if b > threshold:
                break
            good += c
        return good


class QueueWaitTrend:
    """Windowed mean of the ingest queue-wait signal, for the load-shed
    decision (ROADMAP metrics follow-on: shed on queue-wait TREND, not
    instantaneous depth). Bounded (ts, seconds) samples over ``window``
    seconds with an O(1) running sum; fed from the same sites that
    observe ``INGEST_STATS['queue_wait']`` (host turn start + device
    batch start), so a gateway sheds while messages are *waiting long*,
    which depth alone misses when the queue is short but slow-draining.
    Single-loop use only (no locking, like the registry itself)."""

    __slots__ = ("window", "max_samples", "_samples", "_sum")

    def __init__(self, window: float = 5.0, max_samples: int = 4096):
        self.window = window
        self.max_samples = max_samples
        self._samples: deque[tuple[float, float]] = deque()
        self._sum = 0.0

    def note(self, seconds: float, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        self._samples.append((now, seconds))
        self._sum += seconds
        if len(self._samples) > self.max_samples:
            _, v = self._samples.popleft()
            self._sum -= v
        self._evict(now)

    def _evict(self, now: float) -> None:
        cutoff = now - self.window
        samples = self._samples
        while samples and samples[0][0] < cutoff:
            _, v = samples.popleft()
            self._sum -= v

    def mean(self, now: float | None = None) -> float:
        self._evict(time.monotonic() if now is None else now)
        n = len(self._samples)
        return self._sum / n if n else 0.0

    def __len__(self) -> int:
        return len(self._samples)


class CallSiteStats:
    """Per-(grain_class, method) call-site latency/error table — bounded,
    fed by the dispatcher's turn epilogue when ``metrics_enabled`` (one
    dict lookup + four scalar updates per turn; nothing is installed
    when metrics are off). The drill-down an SLO breach needs: which
    grain methods are hot/slow/erroring RIGHT NOW — and the per-class
    load signal the placement-policy compiler direction needs.

    Bounded at ``cap`` distinct sites: method cardinality is static in
    practice, but a pathological dynamic-interface workload must not
    grow an unbounded dict on the turn path — sites past the cap are
    counted in ``overflow`` and dropped. Single-loop use only (no
    locking, like the registry itself)."""

    __slots__ = ("cap", "sites", "overflow")

    def __init__(self, cap: int = 256):
        self.cap = cap
        # (interface, method) -> [count, errors, sum_seconds, max_seconds]
        self.sites: dict[tuple[str, str], list] = {}
        self.overflow = 0

    def note(self, interface: str, method: str, seconds: float,
             error: bool = False) -> None:
        key = (interface, method)
        e = self.sites.get(key)
        if e is None:
            if len(self.sites) >= self.cap:
                self.overflow += 1
                return
            e = self.sites[key] = [0, 0, 0.0, 0.0]
        e[0] += 1
        if error:
            e[1] += 1
        e[2] += seconds
        if seconds > e[3]:
            e[3] = seconds

    def top(self, k: int = 10, by: str = "sum") -> list[dict]:
        """The K hottest call sites, ranked by summed turn seconds
        (``by="sum"``, the load view), call count (``"count"``), errors
        (``"errors"``), or worst single turn (``"max"``)."""
        return self.format_top(
            {f"{i}.{m}": e for (i, m), e in self.sites.items()}, k, by)

    @staticmethod
    def format_top(sites: dict, k: int = 10, by: str = "sum"
                   ) -> list[dict]:
        """Rank + render ``{site_name: [count, errors, sum, max]}`` rows
        (the :meth:`snapshot`/:meth:`merge` wire form) as the top-K
        table — ONE formatter shared by per-silo :meth:`top` and the
        ManagementGrain's cluster merge, so the two views cannot
        drift."""
        idx = {"count": 0, "errors": 1, "sum": 2, "max": 3}[by]
        ranked = sorted(sites.items(), key=lambda kv: kv[1][idx],
                        reverse=True)[:k]
        return [{"site": site, "count": e[0], "errors": e[1],
                 "seconds": round(e[2], 6),
                 "mean_ms": round(e[2] / e[0] * 1e3, 3) if e[0] else 0.0,
                 "max_ms": round(e[3] * 1e3, 3)}
                for site, e in ranked]

    def snapshot(self, k: int | None = None) -> dict:
        """Wire/JSON form for the management fan-out (``k`` bounds the
        payload to the top-K by summed seconds; None ships everything)."""
        items = self.sites.items()
        if k is not None and len(self.sites) > k:
            items = sorted(items, key=lambda kv: kv[1][2],
                           reverse=True)[:k]
        return {"sites": {f"{i}.{m}": list(e) for (i, m), e in items},
                "overflow": self.overflow}

    @staticmethod
    def merge(snapshots) -> dict:
        """Fold per-silo :meth:`snapshot` payloads into one cluster-wide
        table (counts/errors/seconds sum, max takes the max)."""
        out: dict[str, list] = {}
        overflow = 0
        for snap in snapshots:
            overflow += snap.get("overflow", 0)
            for site, e in snap.get("sites", {}).items():
                cur = out.get(site)
                if cur is None:
                    out[site] = list(e)
                else:
                    cur[0] += e[0]
                    cur[1] += e[1]
                    cur[2] += e[2]
                    cur[3] = max(cur[3], e[3])
        return {"sites": out, "overflow": overflow}


# payload-size buckets (bytes) and small-count buckets (batch sizes) for
# the ingest size/shape histograms — pass to StatsRegistry.histogram_with
SIZE_BOUNDS = [64.0, 256.0, 1024.0, 4096.0, 16384.0, 65536.0, 262144.0,
               1048576.0, 4194304.0, float("inf")]
COUNT_BOUNDS = [1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                float("inf")]


class StatsRegistry:
    """Named counters/gauges/histograms (CounterStatistic registry)."""

    def __init__(self) -> None:
        self.counters: dict[str, int] = {}
        self.gauges: dict[str, Callable[[], float]] = {}
        self.histograms: dict[str, Histogram] = {}

    def increment(self, name: str, delta: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + delta

    def get(self, name: str) -> int:
        return self.counters.get(name, 0)

    def register_gauge(self, name: str, fn: Callable[[], float]) -> None:
        self.gauges[name] = fn

    def set_gauge(self, name: str, value: float) -> None:
        """Point-in-time gauge write (IntValueStatistic set-style use —
        e.g. a rebalance round records its outcome once per round rather
        than registering a live callable)."""
        self.gauges[name] = lambda: value

    def gauge(self, name: str) -> float:
        fn = self.gauges.get(name)
        return fn() if fn is not None else 0.0

    def histogram(self, name: str) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram()
        return h

    def histogram_with(self, name: str, bounds: list[float]) -> Histogram:
        """Histogram with non-default bucket bounds (size/count series —
        e.g. ``SIZE_BOUNDS`` for frame bytes); bounds apply on first
        creation only, so call sites can pass them unconditionally."""
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(bounds)
        return h

    def observe(self, name: str, value: float) -> None:
        self.histogram(name).observe(value)

    def snapshot(self) -> dict:
        """Dump for LogStatistics / management queries."""
        return {
            "counters": dict(self.counters),
            "gauges": {k: fn() for k, fn in self.gauges.items()},
            "histograms": {k: h.summary()
                           for k, h in self.histograms.items()},
            "ts": time.time(),
        }
