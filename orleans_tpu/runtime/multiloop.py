"""Multi-loop silo ingress: sharded pump threads + SPSC hand-off rings.

The PR 6-10 batching campaign squeezed per-message cost at every
boundary, and BENCH_r10 still showed ``queue_wait`` at ~0.9 of
per-message stage time at c=32 saturation with the socket pump at
0.33-0.57 of loop wall: **one Python event loop per silo multiplexing
pump + turns + client machinery is the wall**. The reference runtime
never funnels a silo's messaging through one thread — SocketManager
runs dedicated send/receive threads and MessageCenter fans work across
them (SocketManager.cs:1-261, IncomingMessageAcceptor.cs:12).

This module is the asyncio re-design of that split:

* ``IngressLoopPool`` — N ``IngressShard`` threads, each running its
  OWN event loop with its own socket pump. The silo's listener accepts
  on the main loop and hands each accepted socket round-robin to a
  shard (the listener-thread hand-off form of the reference's
  SO_REUSEPORT/acceptor-thread pattern; one process needs no
  SO_REUSEPORT since a single listener can feed every loop).
* Each shard's pump is **vectored**: one ``hotwire.sock_recv_batch`` C
  call per socket-ready event does the recv syscall (GIL released)
  AND the frame-batch decode straight into Message shells — replacing
  the Python recv → buffer-append → decode chain. Without the native
  build (``ORLEANS_TPU_NATIVE=0``) a byte-identical Python fallback
  (``sock_recv`` + ``decode_frames``) pumps the same frames.
* Decoded batches ride a lock-free **SPSC hand-off ring** (single
  producer: the shard thread; single consumer: the silo's main loop)
  with a coalesced ``call_soon_threadsafe`` wakeup, landing in ONE
  ``deliver_batch`` per ring drain entry — so the main loop's share of
  a message shrinks to routing + the turn itself.
* **QoS**: PING/SYSTEM messages (membership probes, control RPCs)
  NEVER enter the ring — each is handed to the main loop immediately
  and individually, so a probe can never sit behind ring backpressure
  or a drain of thousands of application frames (the same split that
  keeps them out of the egress flush accumulator; a probe response
  delayed past the probe timeout gets healthy silos voted dead).
* **Ordering**: a connection's frames stay on ONE shard for the
  connection's lifetime and the ring is FIFO, so per-sender-per-target
  FIFO — the only ordering the wire ever guaranteed — is preserved
  end to end; a grain's traffic from one caller rides one connection
  (senders and gateway clients hash grains to connections), so
  per-grain FIFO holds across any number of ingress loops.
* **Egress for shard-owned connections** (gateway client routes): the
  route's writer is a :class:`ShardWriter` bound to the MAIN loop over
  a dup'd fd — the shard owns only the READ half, so responses encode
  AND write where the fabric already runs with ZERO cross-thread
  hand-offs (this alone was worth ~1.7x on the closed-loop A/B vs
  marshalling writes to the shard), vectored through
  ``hotwire.sock_writev`` (one writev per flush group, no ``b"".join``
  copy) with a buffered Python fallback.

**Sharded egress** (``SiloConfig.egress_shards = N``, ISSUE 15) is the
structural twin of the ingress split for the OUTBOUND half — the PR-11
residue was that every ``encode_message_batch`` call and every
per-endpoint sender write still ran on the main loop:

* :class:`EgressShard` — the egress half of one shard loop: an SPSC
  ring fed FROM the main loop (reverse direction of the ingress rings,
  same coalesced-wakeup/single-writer-counter discipline), draining
  into per-endpoint silo-peer senders and shard-bound client-route
  writers that live ON the shard loop. Encode runs shard-side against
  a per-shard bounded header-template cache (same key/cap as the
  main-loop cache in ``wire.py``), writes ride ``sock_writev``, and
  outbound RESPONSE envelopes are recycled shard-side in one sweep the
  moment their bytes are produced (the freelist release is
  thread-safe — see ``core.message``).
* **Placement** mirrors link ownership (the Mapple mapping philosophy:
  where work runs is a policy over ownership, not an accident of which
  loop created the socket): a silo-peer sender colocates with the
  ingress shard that owns the INBOUND half of the same peering (the
  handshake records ``peer endpoint -> shard``); connect-side links
  with no inbound half round-robin onto shards. With an ingress pool
  the egress shards BORROW the first N ingress loops; without one
  (``ingress_loops=1``) the pool spawns N dedicated egress loop
  threads (client routes then keep the main-loop path — only
  shard-owned routes move).
* **QoS by construction**: PING/SYSTEM messages never enter an egress
  ring (nor the flush accumulator — the PR-10 invariant): they hand
  off per-message via ``call_soon_threadsafe`` straight to the shard's
  sender, so a probe response can never sit behind ring backpressure
  or be dropped by it — the exact mirror of the ingress bypass. Past
  the hand-off it shares the sender's wire FIFO with application
  traffic exactly like the classic path does, but the application
  backlog ahead of it is bounded by the per-endpoint backpressure cap
  below (the classic queue is unbounded — sharding makes the worst
  case strictly tighter, not looser).
* **Backpressure** is bounded in the only direction possible for a
  producer that cannot pause response generation: when ring backlog
  PLUS the destination endpoint's OWN sender-queue occupancy pass
  capacity (a wedged peer blocks its sender mid-write and the queue
  grows behind it), new application messages toward that endpoint DROP
  (counted, ``egress.ring_drops``) — the same
  learn-via-response-timeout semantics as a dead-peer send drop; the
  bound is per-endpoint, so a wedged peer never drops traffic toward
  healthy peers sharing its shard. QoS bypass traffic is never
  dropped; client routes buffer in the shard-bound writer exactly like
  the main-loop transport path does today.
* **Stats discipline**: dwell/encode are STAMPED shard-side into plain
  lists and REPLAYED loop-side over a per-shard stat ring (the
  PR-9/PR-11 loop-confinement rule; the registries are loop-confined,
  so OTPU007 stays clean with zero suppressions).
* **Clean shutdown** mirrors the ingress rings: the pool closes (new
  sends fall back to the classic main-loop path), each shard drains
  its ring inline on its own loop, senders flush their queues
  best-effort, then standalone threads join — pushed == drained.

``egress_shards = 0`` (the default) constructs NONE of this: senders,
encode, and client-route writes stay on the main loop bit for bit (the
A/B lever, symmetric with ``batched_egress``/``ingress_loops``).

``SiloConfig.ingress_loops = 1`` (the default) constructs NONE of this:
the silo keeps today's in-loop ``asyncio.start_server`` pump bit for
bit. ``ingress_loops = N >= 2`` spawns N shard threads. In-process
fabrics (InProcFabric) have no sockets and ignore the knob.

GIL note (honest scaling bounds): the recv/writev syscalls and the
select waits release the GIL; header decode and body deserialize hold
it. 1→2 loops therefore overlaps socket IO and scheduling with turn
execution rather than doubling decode throughput — the A/B ratio in
``benchmarks/loop_attribution.run_multiloop_ab`` is the measurement,
and on free-threaded builds the same structure scales further.
"""

from __future__ import annotations

import asyncio
import contextvars
import logging
import threading
import time
from collections import deque
from typing import TYPE_CHECKING, Any

from ..core import serialization as _ser
from ..core.message import Category, Direction, Message, recycle_messages
from ..observability.stats import (COUNT_BOUNDS, EGRESS_STATS, INGEST_STATS,
                                   SIZE_BOUNDS)
from .wire import (
    _LEN,
    MAX_FRAME_SEGMENT,
    FrameError,
    decode_frames,
    decode_handshake,
    encode_handshake,
    encode_message_batch,
    finish_batch_entries,
    leads_hostile_frame,
    writev_leftover,
)

if TYPE_CHECKING:
    from .silo import Silo
    from .socket_fabric import SocketFabric

log = logging.getLogger("orleans.multiloop")

__all__ = ["IngressLoopPool", "IngressShard", "SpscRing", "ShardWriter",
           "EgressShard", "EgressShardPool", "EgressLoopThread"]

# ring capacity in MESSAGES before the shard pauses its socket reads
# (kernel buffers then backpressure the peer); drained in one consumer
# callback, so this bounds main-loop burst size too
_RING_CAPACITY = 16384
# egress ring capacity in MESSAGES before the main loop starts dropping
# application traffic toward that shard (bounded backpressure — the
# producer is response generation, which cannot pause; see module
# docstring). QoS bypass traffic never counts against (or waits on) it.
_EGRESS_RING_CAPACITY = 16384
_READ_SIZE = 1 << 16
# native vectored entry points (Linux/macOS builds; absent on Windows
# or under ORLEANS_TPU_NATIVE=0 — the Python pump is the fallback)
_HW = _ser._hotwire
_HW_SOCK = _HW is not None and hasattr(_HW, "sock_recv_batch")


class SpscRing:
    """Bounded single-producer/single-consumer hand-off ring with a
    coalesced wakeup: ONE shard thread pushes, the silo's main loop
    drains. ``deque`` append/popleft are GIL-atomic; the armed flag
    coalesces ``call_soon_threadsafe`` wakeups to one per burst (the
    drain clears the flag BEFORE popping, so a push racing the drain
    either lands in the current sweep or re-arms — never lost)."""

    __slots__ = ("_items", "_consumer_loop", "_drain_cb", "_armed",
                 "_context", "pushed_msgs", "drained_msgs",
                 "drained_batches")

    def __init__(self, consumer_loop, drain_cb, context=None):
        self._items: deque = deque()
        self._consumer_loop = consumer_loop
        self._drain_cb = drain_cb
        self._armed = False
        # optional contextvars.Context for the drain callback: asyncio
        # copies the PUSHING thread's context into the Handle, so a
        # ring whose producer runs under an unrelated LOOP_CATEGORY
        # (the egress rings: main loop pushes, shard drains) passes a
        # pre-built context here to keep the consumer-side profiler
        # attribution honest (the profiling pump_ctx idiom). The
        # ingress rings pass none — their shard-thread producer already
        # runs marked "pump", which is exactly the right label.
        self._context = context
        # backlog = pushed - drained: each counter has exactly ONE
        # writer (producer / consumer), so no read-modify-write ever
        # races; the other side only reads (torn-free under the GIL)
        self.pushed_msgs = 0
        self.drained_msgs = 0
        self.drained_batches = 0

    def push(self, item, n_msgs: int) -> None:
        """Producer side (shard thread only)."""
        self._items.append(item)
        self.pushed_msgs += n_msgs
        if not self._armed:
            self._armed = True
            if self._context is not None:
                self._consumer_loop.call_soon_threadsafe(
                    self._drain, context=self._context)
            else:
                self._consumer_loop.call_soon_threadsafe(self._drain)

    def _drain(self) -> None:
        """Consumer side (main loop only)."""
        self._armed = False
        items = self._items
        while True:
            try:
                item = items.popleft()
            except IndexError:
                return
            self.drained_msgs += item[0]
            self.drained_batches += 1
            try:
                self._drain_cb(item)
            except Exception:  # noqa: BLE001 — same contract as the pump
                log.exception("ring drain failed")

    def drain_now(self) -> None:
        """Final consumer-side sweep at shutdown (producers stopped):
        whatever the armed callback never got to runs inline so no
        decoded message is dropped — the clean-shutdown drain."""
        self._drain()

    def discard(self, on_item) -> None:
        """Teardown sweep for a DEAD consumer loop: pop every item
        under the normal counter discipline (pushed == drained still
        holds) but hand it to ``on_item`` instead of the drain
        callback, which must not run in the caller's context."""
        items = self._items
        while True:
            try:
                item = items.popleft()
            except IndexError:
                return
            self.drained_msgs += item[0]
            self.drained_batches += 1
            try:
                on_item(item)
            except Exception:  # noqa: BLE001 — teardown best-effort
                log.exception("ring discard failed")

    def backlog(self) -> int:
        return self.pushed_msgs - self.drained_msgs


async def _read_handshake_frame(loop, sock) -> tuple[bytes, bytes]:
    """Read ONE length-prefixed frame from a raw non-blocking socket
    (the connection-opening handshake); returns (headers, leftover) —
    any bytes the peer pipelined behind the handshake seed the pump's
    tail. Raises FrameError on a hostile announcement, ConnectionError
    on EOF mid-frame."""
    buf = bytearray()
    while True:
        if len(buf) >= 8:
            hlen, blen = _LEN.unpack_from(buf, 0)
            if hlen > MAX_FRAME_SEGMENT or blen > MAX_FRAME_SEGMENT:
                raise FrameError(f"oversized frame announced: {hlen}+{blen}")
            total = 8 + hlen + blen
            if len(buf) >= total:
                return bytes(buf[8:8 + hlen]), bytes(buf[total:])
        chunk = await loop.sock_recv(sock, _READ_SIZE)
        if not chunk:
            raise ConnectionError("EOF during handshake")
        buf += chunk


class ShardWriter:
    """Writer for the client route of a shard-owned connection, bound
    to ONE loop over a dup'd fd: the silo's MAIN loop by default (the
    shard thread owns the READ half; responses encode AND write where
    the fabric's client-route paths already run, so the response path
    pays ZERO cross-thread hand-offs), or — under sharded egress — the
    connection's OWN shard loop (``egress_shard`` set; the fabric then
    hands whole Message flush groups across the egress ring and the
    shard encodes + writes them here). The dup keeps
    the write fd safe against kernel fd-number reuse after the shard
    closes its half; writes to a peer-closed socket surface as EPIPE
    and drop the route exactly like the StreamWriter path. Egress is
    vectored: one ``sock_writev`` per flush group on the native build
    (no ``b"".join`` copy), buffered ``sock_sendall`` otherwise.
    Mirrors the StreamWriter surface the fabric uses
    (``write``/``close``/``is_closing``)."""

    __slots__ = ("_loop", "_sock", "_chunks", "_sending", "_task",
                 "_closed", "on_error", "egress_shard")

    def __init__(self, main_loop, sock):
        self._loop = main_loop
        # set by the shard handler when sharded egress owns this route:
        # the fabric then feeds Message lists to that shard's ring (and
        # the writer binds to the SHARD loop instead of the main loop)
        self.egress_shard = None
        # portable duplicate of the WRITE half: socket.dup() (not
        # os.dup on the raw fd — fds aren't WinSock handles on Windows)
        self._sock = sock.dup()
        self._sock.setblocking(False)
        self._chunks: list = []
        self._sending = False
        self._task = None         # in-flight _send_loop task
        self._closed = False
        self.on_error = None      # main-loop thunk: route cleanup

    # -- main-loop surface ----------------------------------------------
    def write(self, data: bytes) -> None:
        if self._closed:
            raise ConnectionResetError("shard connection closed")
        self._chunks.append(data)
        if not self._sending:
            self._sending = True
            self._task = self._loop.create_task(self._send_loop())

    def write_many(self, chunks: list) -> None:
        """Batched write (``_write_client_batch``): the chunk list rides
        to ``sock_writev`` as-is — no ``b"".join`` copy anywhere on the
        native egress path."""
        if self._closed:
            raise ConnectionResetError("shard connection closed")
        self._chunks.extend(chunks)
        if not self._sending:
            self._sending = True
            self._task = self._loop.create_task(self._send_loop())

    def close(self) -> None:
        """Thread-safe: callable from the main loop (route teardown) or
        the shard's connection handler (peer EOF)."""
        if self._closed:
            return
        self._closed = True
        try:
            self._loop.call_soon_threadsafe(self._do_close)
        except RuntimeError:
            self._do_close()  # main loop gone (process teardown)

    def is_closing(self) -> bool:
        return self._closed

    def _do_close(self) -> None:
        # cancel a send parked in sock_sendall FIRST: closing the fd
        # silently removes it from the selector, so the writability
        # event that future waits on would never fire and the task (plus
        # its buffered responses) would leak for the silo's lifetime
        t = self._task
        if t is not None and not t.done():
            t.cancel()
        self._chunks.clear()
        try:
            self._sock.close()
        except OSError:
            pass

    async def _send_loop(self) -> None:
        loop = self._loop
        try:
            while self._chunks and not self._closed:
                chunks, self._chunks = self._chunks, []
                if _HW_SOCK:
                    # vectored egress: one writev per flush group; a
                    # partial write falls back to buffered sendall for
                    # the remainder (rare: kernel buffer full)
                    try:
                        sent = _HW.sock_writev(self._sock.fileno(), chunks)
                    except BlockingIOError:
                        sent = 0
                    rest = writev_leftover(chunks, sent)
                    if rest:
                        await loop.sock_sendall(self._sock, rest)
                else:
                    await loop.sock_sendall(self._sock, b"".join(chunks))
        except (OSError, ValueError) as e:
            self._closed = True
            log.info("shard client route write failed: %s", e)
            hook = self.on_error
            if hook is not None:
                hook()
        finally:
            self._sending = False


class IngressShard(threading.Thread):
    """ONE ingress loop: a daemon thread running its own event loop,
    pumping the sockets assigned to it and handing decoded batches to
    the silo's main loop over its SPSC ring. The MessageCenter ingress
    shard of the tentpole design: routing stays sharded because a
    connection pins here for life and grain→connection affinity is
    hash-based at every sender."""

    def __init__(self, pool: "IngressLoopPool", index: int):
        super().__init__(name=f"{pool.silo.config.name}-ingress-{index}",
                         daemon=True)
        self.pool = pool
        self.index = index
        # wire-charge route label (cost attribution): per-shard, not
        # per-peer — a shard owns its connections for life, so the label
        # is stable and costs one tuple slot per ring entry
        self._route = f"in:shard{index}"
        self.main_loop = pool.main_loop
        self.loop = asyncio.new_event_loop()
        self.ring = SpscRing(self.main_loop, pool._drain_entry)
        self.profiler = None
        self._conn_tasks: set = set()
        self._ready = threading.Event()
        # counters read by tests/benchmarks (single-writer: this thread)
        self.qos_direct = 0       # PING/SYSTEM handed off ring-free
        self.batches = 0          # ring entries pushed
        self.frames = 0           # messages decoded on this loop

    # -- thread body -----------------------------------------------------
    def run(self) -> None:
        asyncio.set_event_loop(self.loop)
        cfg = self.pool.silo.config
        if cfg.profiling_enabled:
            # per-loop attribution: each ingress loop gets its OWN
            # profiler (occupancy is a loop property); ctl_loop_profile
            # aggregates them beside the main loop's. Best-effort: a
            # failed install must not kill the shard (submit_conn drops
            # connections of a never-ready shard on the floor)
            try:
                from ..observability.profiling import (
                    install_loop_profiler, mark_loop_category)
                self.profiler = install_loop_profiler(
                    self.loop, window=cfg.profiling_window,
                    ring=cfg.profiling_ring, top_k=cfg.profiling_top_k,
                    trigger_interval=cfg.profiling_trigger_interval)
                mark_loop_category("pump")
            except Exception:  # noqa: BLE001
                log.exception("ingress-loop profiler install failed; "
                              "shard runs unprofiled")
        self._ready.set()
        try:
            self.loop.run_forever()
        finally:
            # reap connection tasks (their finallys close the sockets
            # and unregister client routes), then close the loop
            pending = [t for t in self._conn_tasks if not t.done()]
            for t in pending:
                t.cancel()
            if pending:
                try:
                    self.loop.run_until_complete(asyncio.gather(
                        *pending, return_exceptions=True))
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            self.loop.close()

    def submit_conn(self, fabric: "SocketFabric", silo: "Silo",
                    sock) -> None:
        """Main-loop side: hand one accepted socket to this shard. Never
        blocks: the pool's start() already waited for readiness — a
        shard whose thread died before becoming ready just closes the
        socket (the client redials another connection), it must not
        stall the acceptor (a frozen main loop delays PING responses
        past the probe timeout — the failure the QoS split prevents)."""
        if self.pool.closed or not (self._ready.is_set() and
                                    self.is_alive()):
            sock.close()
            if not self.pool.closed:
                log.warning("ingress shard %s not serving; connection "
                            "dropped", self.name)
            return

        def _start() -> None:
            t = self.loop.create_task(self._serve_conn(fabric, silo, sock))
            self._conn_tasks.add(t)
            t.add_done_callback(self._conn_tasks.discard)

        try:
            self.loop.call_soon_threadsafe(_start)
        except RuntimeError:
            sock.close()  # shard stopped between check and submit

    def stop(self) -> None:
        try:
            self.loop.call_soon_threadsafe(self.loop.stop)
        except RuntimeError:
            pass

    # -- shard-loop connection handling ---------------------------------
    async def _serve_conn(self, fabric: "SocketFabric", silo: "Silo",
                          sock) -> None:
        """Shard-side twin of ``SocketFabric._handle_conn``: handshake,
        route registration, then the vectored pump."""
        from ..observability.profiling import mark_loop_category
        mark_loop_category("pump")
        loop = self.loop
        peer_addr = None
        is_client = False
        writer: ShardWriter | None = None
        try:
            headers, tail = await _read_handshake_frame(loop, sock)
            hs = decode_handshake(headers)
            peer_addr = hs["address"]
            is_client = hs["kind"] == "client"
            await loop.sock_sendall(
                sock, encode_handshake("silo", silo.silo_address))
            if is_client:
                # gateway route for a shard-owned connection: the WRITE
                # half binds to the main loop over a dup'd fd (zero
                # cross-thread hops on the response path; one writev
                # per flush group). Route dict mutation is MARSHALLED
                # to the main loop — the fabric's route tables are
                # main-loop state (unregister_silo iterates them) — and
                # the pump does not START until the registration has
                # RUN there: call_soon_threadsafe FIFO alone is not
                # enough, because a ring already armed by another
                # connection on this shard has its drain queued AHEAD
                # of the registration callback and would route a
                # pipelined first request (whose response then finds no
                # route) before it. One confirmation round trip per
                # connection setup buys the ordering for every delivery
                # path — ring, QoS-direct, and bounce alike.
                #
                # Sharded egress: when the egress pool rides the ingress
                # shards and covers this one, the write half binds to
                # THIS shard's loop instead — the fabric then hands
                # whole Message flush groups across the shard's egress
                # ring (one coalesced hop per group) and encode + writev
                # run here, off the main loop.
                eshard = None
                epool = getattr(fabric, "egress_pool", None)
                if epool is not None and epool.on_ingress and \
                        not epool.closed and \
                        self.index < len(epool.shards) and \
                        epool.shards[self.index].loop is self.loop:
                    # loop identity, not index alone: the fabric-wide
                    # pool borrows the FIRST registered silo's ingress
                    # loops — a co-hosted silo's shard at the same index
                    # runs on a different thread, and binding its writer
                    # there would make write_many a cross-thread call
                    eshard = epool.shards[self.index]
                writer = ShardWriter(
                    self.loop if eshard is not None else self.main_loop,
                    sock)
                writer.egress_shard = eshard

                def _on_err(w=writer, f=fabric, a=peer_addr,
                            ml=self.main_loop):
                    # route-dict mutation MARSHALS to the main loop with
                    # the is-ours identity check (same rule as _cleanup
                    # below): under sharded egress this hook fires on
                    # the SHARD loop, and a reconnected client may have
                    # registered a NEW route meanwhile
                    def _drop():
                        if f.client_routes.get(a) is w:
                            f._drop_client_route(a)
                    try:
                        ml.call_soon_threadsafe(_drop)
                    except RuntimeError:
                        pass  # main loop gone: process teardown
                    w._do_close()

                writer.on_error = _on_err
                native = bool(hs.get("hotwire", False))
                registered: asyncio.Future = loop.create_future()

                def _register(f=fabric, a=peer_addr, w=writer,
                              owner=silo.silo_address, n=native):
                    f.client_routes[a] = w
                    f._route_owner[a] = owner
                    f._client_native[a] = n
                    try:
                        self.loop.call_soon_threadsafe(
                            lambda: registered.done()
                            or registered.set_result(None))
                    except RuntimeError:
                        pass  # shard stopping: the await below is dying

                self.main_loop.call_soon_threadsafe(_register)
                await registered
            else:
                # silo peer: record which shard owns the inbound half of
                # this peering so the egress pool colocates the OUTBOUND
                # sender with it (link-ownership affinity; marshalled —
                # the map is main-loop state like the route tables)
                try:
                    self.main_loop.call_soon_threadsafe(
                        fabric._record_peer_shard, peer_addr.endpoint,
                        self.index)
                except RuntimeError:
                    pass  # main loop gone: process teardown
            await self._pump(fabric, silo, sock, bytearray(tail))
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # clean EOF / peer died
        except FrameError as e:
            log.warning("dropping shard connection from %s: %s",
                        peer_addr, e)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            log.exception("shard connection handler failed (peer=%s)",
                          peer_addr)
        finally:
            if is_client and peer_addr is not None and writer is not None:
                # route cleanup on the main loop (same marshalling rule
                # as registration; the is-ours identity check must run
                # where the dict is owned — a reconnected client may
                # have re-registered a NEW route meanwhile)
                def _cleanup(f=fabric, a=peer_addr, w=writer):
                    if f.client_routes.get(a) is w:
                        f.client_routes.pop(a, None)
                        f._route_owner.pop(a, None)
                        f._client_native.pop(a, None)

                try:
                    self.main_loop.call_soon_threadsafe(_cleanup)
                except RuntimeError:
                    pass  # main loop gone: process teardown
            elif not is_client and peer_addr is not None:
                try:
                    self.main_loop.call_soon_threadsafe(
                        fabric._forget_peer_shard, peer_addr.endpoint,
                        self.index)
                except RuntimeError:
                    pass  # main loop gone: process teardown
            if writer is not None:
                writer.close()  # releases the dup'd write fd
            try:
                sock.close()
            except OSError:
                pass

    async def _pump(self, fabric, silo, sock, tail: bytearray) -> None:
        """The sharded socket pump. Native build: a PERSISTENT reader
        callback — one ``add_reader`` for the connection's lifetime, and
        each socket-ready event costs exactly one vectored C call
        (recv + frame-batch decode) plus the ring push, with no
        coroutine resumption or per-read selector churn (the same
        persistent ``_read_ready`` shape the transport layer uses).
        Fallback: byte-identical ``sock_recv`` + ``decode_frames``.
        Backpressure: when the ring backs up past capacity the pump
        unregisters the reader (kernel buffers then slow the peer)
        instead of growing the hand-off unboundedly."""
        loop = self.loop
        if tail:
            # frames the peer pipelined behind its handshake: decode the
            # seeded tail NOW — both pump shapes below only parse after
            # a fresh recv, so without this a conformant peer that sent
            # handshake+request in one burst and then waited for the
            # response would hang until its timeout
            consumed, msgs0, bounces0 = decode_frames(tail)
            if consumed:
                del tail[:consumed]
            if msgs0 or bounces0:
                self._deliver(fabric, silo, msgs0, bounces0, 0.0, consumed)
            if leads_hostile_frame(tail):
                raise FrameError("oversized frame announced")
        if not _HW_SOCK:
            buf = bytearray(tail)
            while True:
                while self.ring.backlog() > _RING_CAPACITY:
                    await asyncio.sleep(0.002)
                chunk = await loop.sock_recv(sock, _READ_SIZE)
                if not chunk:
                    if buf:
                        raise asyncio.IncompleteReadError(bytes(buf), None)
                    return
                buf += chunk
                # decode stage timed AROUND the parse only — the recv
                # await above is socket idle, not decode cost (the
                # replayed observation must match the single-loop
                # path's decode_frames-internal timing)
                t0 = time.monotonic()
                consumed, msgs, bounces = decode_frames(buf)
                decode_s = time.monotonic() - t0
                if consumed:
                    del buf[:consumed]
                if msgs or bounces:
                    self._deliver(fabric, silo, msgs, bounces,
                                  decode_s, consumed)
                if leads_hostile_frame(buf):
                    raise FrameError("oversized frame announced")

        fd = sock.fileno()
        done: asyncio.Future = loop.create_future()
        tail_b = bytes(tail)

        def _finish(exc: BaseException | None) -> None:
            try:
                loop.remove_reader(fd)
            except Exception:  # noqa: BLE001 — already removed/closed
                pass
            if not done.done():
                if exc is None:
                    done.set_result(None)
                else:
                    done.set_exception(exc)

        def on_ready() -> None:
            nonlocal tail_b
            # decode-stage timing covers the whole fused C call: the
            # NONBLOCKING recv syscall is indivisible from the parse
            # here (that fusion is the vectored pump's point), so the
            # replayed decode observation includes ~1-2us of syscall
            # the decode_frames-timed paths don't — noted, accepted
            t0 = time.monotonic()
            # adaptive read size: sock_recv_batch round-trips the
            # partial tail through a fresh buffer each call, so a huge
            # mid-flight frame read in fixed 64K steps would cost
            # O(frame^2/64K) memcpy — scaling the read toward the tail
            # size keeps the total near-linear (cap 4MB per event)
            bufsize = _READ_SIZE
            tl = len(tail_b)
            if tl > bufsize:
                bufsize = tl if tl < (1 << 22) else (1 << 22)
            try:
                r = _HW.sock_recv_batch(fd, tail_b, Message, bufsize)
            except ValueError as e:
                _finish(FrameError(str(e)))
                return
            except OSError as e:
                _finish(e)
                return
            if r is None:
                return  # spurious readiness
            entries, tail2, eof, nrecv = r
            msgs: list = []
            bounces: list = []
            finish_batch_entries(entries, msgs, bounces)
            nbytes = len(tail_b) + nrecv - len(tail2)  # consumed bytes
            tail_b = tail2
            if msgs or bounces:
                self._deliver(fabric, silo, msgs, bounces,
                              time.monotonic() - t0, nbytes)
            if leads_hostile_frame(tail_b):
                _finish(FrameError("oversized frame announced"))
                return
            if eof:
                _finish(asyncio.IncompleteReadError(tail_b, None)
                        if tail_b else None)
                return
            if self.ring.backlog() > _RING_CAPACITY:
                # backpressure: stop reading; the kernel buffer fills
                # and slows the peer. Resume once the consumer drains.
                try:
                    loop.remove_reader(fd)
                except Exception:  # noqa: BLE001
                    pass
                loop.call_later(0.002, _resume)

        def _resume() -> None:
            if done.done():
                return
            if self.ring.backlog() > _RING_CAPACITY:
                loop.call_later(0.002, _resume)
                return
            loop.add_reader(fd, on_ready)
            on_ready()  # bytes may have buffered while paused

        loop.add_reader(fd, on_ready)
        try:
            await done
        finally:
            if not done.done():
                # the TASK was cancelled (shard stopping) with `done`
                # still pending: resolve it so a backpressure `_resume`
                # scheduled via call_later no-ops instead of re-arming
                # add_reader on the closed fd
                done.cancel()
            try:
                loop.remove_reader(fd)
            except Exception:  # noqa: BLE001 — loop/socket tearing down
                pass

    def _deliver(self, fabric, silo, msgs: list, bounces: list,
                 decode_s: float, nbytes: int) -> None:
        """Hand one decoded read to the main loop: PING/SYSTEM peel off
        ring-free (the QoS split), everything else rides ONE ring entry;
        decode-stage metrics replay loop-side at drain (StatsRegistry is
        not thread-safe — the PR-9 stamp-off-loop/replay-loop-side
        rule)."""
        now = time.monotonic()
        n = len(msgs) + len(bounces)
        self.frames += n
        app: list | None = None
        main = self.main_loop
        for m in msgs:
            m.received_at = now
            if m.category is not Category.APPLICATION:
                # QoS: probes/control RPCs must never wait behind ring
                # backpressure or an application drain — immediate
                # per-message hand-off (still FIFO with prior ring
                # entries only via the ready queue, which is exactly
                # the cross-category looseness the category-partitioned
                # inbound queues already allow)
                self.qos_direct += 1
                main.call_soon_threadsafe(fabric._route_inbound, silo, m)
            else:
                if app is None:
                    app = []
                app.append(m)
        for e in bounces:
            e.message.received_at = now
            main.call_soon_threadsafe(fabric._bounce_undecodable,
                                      e.message, str(e))
        if app is not None or (n and (self.pool._ist is not None or
                                      self.pool._led is not None)):
            # an entry rides even for QoS/bounce-only reads when metrics
            # (or the cost ledger) are on: the decode seconds/bytes, the
            # ALL-category frame counts, and the wire-byte charge must
            # replay loop-side exactly like the single-loop decode_frames
            # observations (only the stats ride the ring then — the QoS
            # messages themselves were already handed off above, ring-free)
            self.batches += 1
            n_app = len(app) if app is not None else 0
            self.ring.push((n_app, silo, app or [], decode_s, nbytes, n,
                            self._route), n_app)


class IngressLoopPool:
    """N ingress shards for one silo + the round-robin assigner the
    listener uses. Constructed by ``SocketFabric.register_silo`` when
    ``SiloConfig.ingress_loops >= 2``; ``Silo.stop`` closes it (pump
    threads joined, rings drained) BEFORE the message center stops so
    every decoded message still delivers — the clean-shutdown drain."""

    def __init__(self, silo: "Silo", n: int):
        self.silo = silo
        self.main_loop = asyncio.get_running_loop()
        self.closed = False
        self.accept_handle: Any = None   # set by the fabric's acceptor
        self._rr = 0
        # ingest stage metrics replayed at drain (loop-side)
        self._ist = silo.ingest_stats
        # cost ledger, same replay rule: shards stamp nbytes into the
        # ring entry, the drain charges the route loop-side
        self._led = silo.ledger
        self.shards = [IngressShard(self, i) for i in range(n)]

    def start(self) -> None:
        for s in self.shards:
            s.start()
        for s in self.shards:
            s._ready.wait(5.0)

    def assign(self) -> IngressShard:
        self._rr = (self._rr + 1) % len(self.shards)
        return self.shards[self._rr]

    # -- consumer side (main loop) --------------------------------------
    def _drain_entry(self, item) -> None:
        """One ring entry → one ``deliver_batch`` routing hop, with the
        decode-stage metrics the shard stamped replayed here (loop-side,
        the only thread the registry tolerates). ``n_total`` counts
        EVERY frame of the read — QoS-bypassed and bounced included —
        matching the single-loop ``decode_frames`` observations."""
        _n, silo, msgs, decode_s, nbytes, n_total, route = item
        ist = self._ist
        if ist is not None and n_total:
            ist.observe(INGEST_STATS["decode"], decode_s)
            ist.histogram_with(INGEST_STATS["decode_bytes"],
                               SIZE_BOUNDS).observe(nbytes)
            ist.increment(INGEST_STATS["frames"], n_total)
            ist.histogram_with(INGEST_STATS["frame_batch"],
                               COUNT_BOUNDS).observe(n_total)
        led = self._led
        if led is not None and nbytes:
            led.charge_wire(route, rx=nbytes)
        if msgs:
            silo.fabric._route_inbound_batch(silo, msgs)

    # -- lifecycle -------------------------------------------------------
    def close_acceptor(self) -> None:
        h = self.accept_handle
        if h is not None:
            self.accept_handle = None
            h()

    def close(self) -> None:
        """Synchronous teardown half (fabric unregister): stop accepting
        and stop the shard loops."""
        self.closed = True
        self.close_acceptor()
        for s in self.shards:
            s.stop()

    async def aclose(self) -> None:
        """Full teardown (silo stop): stop accepts + pump loops, join
        the threads, then drain every ring on the main loop so decoded
        messages still reach the (still-running) message center."""
        self.close()
        loop = asyncio.get_running_loop()
        for s in self.shards:
            if s.is_alive():
                await loop.run_in_executor(None, s.join, 5.0)
            if s.is_alive():
                # a wedged shard (e.g. a callback deserializing a huge
                # body) outlived the join budget: its ring drain below
                # is best-effort only — say so instead of silently
                # racing the producer
                log.warning("ingress shard %s did not stop within 5s; "
                            "draining its ring best-effort", s.name)
        for s in self.shards:
            s.ring.drain_now()

    # -- observability ---------------------------------------------------
    async def loop_profiles(self, windows: int = 8) -> list[dict]:
        """Per-ingress-loop occupancy profiles (the per-loop attribution
        the profiler's per-loop install buys; aggregated beside the main
        loop's profile by ``SiloControl.ctl_loop_profile``). Each
        profile is read ON its own loop — the profiler's dicts are
        loop-confined, exactly like the main loop's — with a direct read
        only once the shard thread is provably dead."""
        out = []
        for s in self.shards:
            p = s.profiler
            if p is None:
                continue
            if s.is_alive():
                async def _read(prof=p, w=windows):
                    return prof.profile(w, snapshots=False)
                try:
                    prof = await asyncio.wait_for(asyncio.wrap_future(
                        asyncio.run_coroutine_threadsafe(_read(), s.loop)),
                        timeout=2.0)
                except Exception:  # noqa: BLE001 — shard stopping mid-read
                    continue
            else:
                prof = p.profile(windows, snapshots=False)
            prof["ingress_loop"] = s.index
            prof["frames"] = s.frames
            prof["qos_direct"] = s.qos_direct
            prof["ring_batches"] = s.batches
            out.append(prof)
        return out


# ---------------------------------------------------------------------------
# Sharded egress (ISSUE 15): the outbound twin of the ingress shards
# ---------------------------------------------------------------------------

class EgressLoopThread(threading.Thread):
    """A dedicated egress shard loop for silos WITHOUT an ingress pool
    (``egress_shards > 0`` with ``ingress_loops = 1``): thread + event
    loop + optional per-loop profiler, nothing else — the pump half of
    :class:`IngressShard` never exists here. With an ingress pool the
    egress shards borrow its loops instead (link-ownership affinity)."""

    def __init__(self, name: str, profiling_cfg=None):
        super().__init__(name=name, daemon=True)
        self.loop = asyncio.new_event_loop()
        self.profiler = None
        self._profiling_cfg = profiling_cfg
        self._ready = threading.Event()

    def run(self) -> None:
        asyncio.set_event_loop(self.loop)
        cfg = self._profiling_cfg
        if cfg is not None:
            try:  # best-effort, like the ingress shards
                from ..observability.profiling import (
                    install_loop_profiler, mark_loop_category)
                self.profiler = install_loop_profiler(
                    self.loop, window=cfg.profiling_window,
                    ring=cfg.profiling_ring, top_k=cfg.profiling_top_k,
                    trigger_interval=cfg.profiling_trigger_interval)
                mark_loop_category("egress")
            except Exception:  # noqa: BLE001
                log.exception("egress-loop profiler install failed; "
                              "shard runs unprofiled")
        self._ready.set()
        try:
            self.loop.run_forever()
        finally:
            self.loop.close()

    def stop(self) -> None:
        try:
            self.loop.call_soon_threadsafe(self.loop.stop)
        except RuntimeError:
            pass


# egress ring entry kinds (item[0] is the message count the SpscRing
# counters track; QoS bypass traffic never rides the ring)
_EG_PEER = 0     # (n, _EG_PEER, endpoint, Message | [Message])
_EG_CLIENT = 1   # (n, _EG_CLIENT, (addr, writer, native), [Message])

_EGRESS_ENCODE_STAT = EGRESS_STATS["encode"]
_EGRESS_DWELL_STAT = EGRESS_STATS["dwell"]

# wire-charge stamp riding the egress stat rings (cost attribution):
# replayed into the loop-confined CostLedger by _apply_stats
from ..observability.ledger import WIRE_STAMP as _LEDGER_WIRE  # noqa: E402


class EgressShard:
    """The egress half of ONE shard loop: an SPSC ring fed from the main
    loop draining into per-endpoint silo-peer senders and shard-bound
    client-route writers that live on this loop; shard-side
    ``encode_message_batch`` against a per-shard template cache;
    encode-then-recycle for outbound responses; dwell/encode stamped
    here and replayed loop-side over ``stat_ring`` (the loop-confinement
    rule). Feed methods (``feed_*``/``*_direct``) run on the MAIN loop
    only (single producer); ``_drain``/``_*_now`` run on the shard loop
    only (single consumer)."""

    def __init__(self, pool: "EgressShardPool", index: int, loop):
        self.pool = pool
        self.fabric = pool.fabric
        self.index = index
        self.loop = loop
        self.main_loop = pool.main_loop
        # drain in a pre-built "egress" context: the PRODUCER is the
        # main loop (running under "turns"/whatever category scheduled
        # the flush) and call_soon_threadsafe would copy that context
        # into the shard-side drain — mislabeling the moved encode +
        # write work on the shard's own profiler (the ingress rings
        # don't need this: their shard-thread producer is marked
        # "pump", the right label for main-loop routing)
        from ..observability.profiling import LOOP_CATEGORY
        ctx = contextvars.Context()
        ctx.run(LOOP_CATEGORY.set, "egress")
        self._egress_ctx = ctx
        self.ring = SpscRing(loop, self._drain, context=ctx)
        # shard -> main-loop stat replay (consumer = MAIN loop): entries
        # are (0, [(series_name, value), ...]) observe stamps — replayed
        # under "observability", the registry-work label, not whatever
        # category the shard thread happened to be in at push time
        obs_ctx = contextvars.Context()
        obs_ctx.run(LOOP_CATEGORY.set, "observability")
        self.stat_ring = SpscRing(pool.main_loop, pool._apply_stats,
                                  context=obs_ctx)
        # per-shard bounded header-template cache (same key/cap rules as
        # wire.py's main-loop cache — wire._frame_template enforces them)
        self.tmpl_cache: dict = {}
        self._senders: dict = {}   # endpoint -> _Sender (shard-confined)
        # counters: single-writer discipline like the ingress shards —
        # qos_direct/encoded/recycled written by the shard thread only,
        # drops by the main loop only
        self.qos_direct = 0
        self.encoded = 0      # wire batches encoded shard-side
        self.recycled = 0     # response envelopes recycled shard-side
        self.drops = 0        # ring-full drops (main-loop writer)
        # application messages sitting in shard SENDER queues, PER
        # endpoint (shard thread is the only writer: _drain increments,
        # the sender's batch pop decrements, _close_endpoint drops the
        # key). feed_peer bounds on ring backlog + the ENDPOINT's own
        # entry — without it the ring drains instantly into the
        # unbounded sender queue and the advertised wedged-peer
        # backpressure would never engage (only a stalled shard loop
        # would); per-endpoint, not shard-wide, so one wedged peer's
        # backlog never drops traffic toward healthy peers sharing the
        # shard (the classic path isolates per-endpoint too)
        self.pending: dict = {}

    # -- main-loop (producer) side ---------------------------------------
    def feed_peer(self, endpoint: str, payload, n: int) -> bool:
        """One application message or one flush group toward a silo
        peer. False = backlog over capacity, payload dropped (bounded
        backpressure; the caller counts/recycles). The bound covers the
        ring AND this ENDPOINT's shard sender queue (``pending``): a
        wedged peer blocks its sender in ``drain()`` while the queue
        behind it grows — that queue, not the instantly-drained ring,
        is where a peer stall accumulates, and it is per-endpoint so a
        wedged peer never drops traffic toward its shard-mates."""
        if self.ring.backlog() + self.pending.get(endpoint, 0) > \
                _EGRESS_RING_CAPACITY:
            self.drops += n
            return False
        self.ring.push((n, _EG_PEER, endpoint, payload), n)
        return True

    def feed_client(self, addr, writer, native: bool, msgs: list) -> None:
        """One response flush group toward a shard-owned client route
        (the Message list crosses the ring; encode happens shard-side).
        Never drops: client responses buffer — in the ring, then the
        shard-bound writer — exactly like the classic path buffers them
        in the transport (the module contract); the peer-side drop
        policy exists for senders whose backlog a wedged PEER grows,
        which a client route, drained by its own shard loop, cannot."""
        n = len(msgs)
        self.ring.push((n, _EG_CLIENT, (addr, writer, native), msgs), n)

    def peer_direct(self, endpoint: str, msg) -> None:
        """QoS bypass (PING/SYSTEM): per-message hand-off straight to
        the shard sender's queue — never through the ring, so a probe
        response cannot sit behind ring backpressure or be dropped by
        the bounded-backpressure check (the egress mirror of the
        ingress QoS split). It shares the sender's wire FIFO from
        there, like the classic path — with the application backlog
        ahead of it capped by the per-endpoint ``feed_peer`` bound."""
        self.loop.call_soon_threadsafe(self._peer_now, endpoint, msg,
                                       context=self._egress_ctx)

    def client_direct(self, addr, writer, native: bool, msg) -> None:
        """QoS bypass for a shard-owned client route: per-message
        encode + write marshalled to the shard, ring-free."""
        self.loop.call_soon_threadsafe(self._client_now, addr, writer,
                                       native, msg,
                                       context=self._egress_ctx)

    # -- shard-loop (consumer) side --------------------------------------
    def _sender(self, endpoint: str):
        s = self._senders.get(endpoint)
        if s is None:
            from .socket_fabric import _Sender
            s = self._senders[endpoint] = _Sender(self.fabric, endpoint,
                                                  shard=self)
        return s

    def _peer_now(self, endpoint: str, msg) -> None:
        self.qos_direct += 1
        self._sender(endpoint).queue.put_nowait(msg)

    def _drain(self, item) -> None:
        kind = item[1]
        if kind == _EG_PEER:
            ep = item[2]
            q = self._sender(ep).queue
            payload = item[3]
            self.pending[ep] = self.pending.get(ep, 0) + item[0]
            if type(payload) is list:
                for m in payload:
                    q.put_nowait(m)
            else:
                q.put_nowait(payload)
        else:
            addr, writer, native = item[2]
            self._write_client(addr, writer, native, item[3])

    def _client_now(self, addr, writer, native: bool, msg) -> None:
        self._write_client(addr, writer, native, [msg])

    def _write_client(self, addr, writer, native: bool,
                      msgs: list) -> None:
        """Shard-side client-route flush: dwell stamp → one
        ``encode_message_batch`` against the per-shard template cache →
        one ``write_many`` (→ ``sock_writev``) → one recycle sweep for
        the now-dead outbound response envelopes. Registry writes are
        forbidden here — stamps replay loop-side."""
        stamps = self._dwell_stamps(msgs)
        fabric = self.fabric
        t0 = time.monotonic()
        chunks = encode_message_batch(
            msgs,
            lambda m, e: fabric._client_encode_error(addr, writer, m, e,
                                                     native),
            native=native, stats=None, templates=fabric.response_templates,
            tmpl_cache=self.tmpl_cache)
        if chunks:
            if stamps is not None:
                stamps.append((_EGRESS_ENCODE_STAT,
                               time.monotonic() - t0))
                if fabric.ledger is not None:
                    stamps.append((_LEDGER_WIRE,
                                   (f"client:{addr}",
                                    sum(len(c) for c in chunks))))
            self.encoded += 1
            write_many = getattr(writer, "write_many", None)
            if write_many is None:
                # main-loop StreamWriter under standalone egress
                # (ingress_loops=1): the encode above already ran HERE,
                # off the main loop — the multi-loop residue fix. Only
                # the final fd write marshals back; the fabric tail
                # handles the disconnected-client drop on its own loop.
                try:
                    self.main_loop.call_soon_threadsafe(
                        fabric._stream_write_client, addr, writer,
                        b"".join(chunks))
                except RuntimeError:
                    pass  # main loop closed: route dying anyway
            else:
                try:
                    write_many(chunks)
                except Exception:  # noqa: BLE001 — client gone mid-write
                    log.info("dropping shard batch to disconnected "
                             "client %s", addr)

                    def _drop(f=fabric, a=addr, w=writer):
                        # is-ours identity check (same rule as _on_err):
                        # by the time this runs on the main loop a
                        # reconnected client may have registered a NEW
                        # route under addr
                        if f.client_routes.get(a) is w:
                            f._drop_client_route(a)
                    try:
                        self.main_loop.call_soon_threadsafe(_drop)
                    except RuntimeError:
                        pass
        self._recycle_responses(msgs)
        if stamps:
            self.stat_ring.push((0, stamps), 0)

    def _dwell_stamps(self, msgs: list):
        """Dwell = accumulator add → shard encode (covers accumulator +
        egress ring transit — strictly MORE truthful than the main-loop
        flush-time observation it replaces for sharded destinations).
        Returns a stamp list when metrics are on, else None; clears the
        send-side ``received_at`` either way."""
        if self.fabric.egress_stats is None:
            for m in msgs:
                m.received_at = None
            # ledger-only mode: the wire charge still needs a stamp list
            # to ride the stat ring when metrics are off
            return [] if self.fabric.ledger is not None else None
        stamps: list = []
        now = time.monotonic()
        for m in msgs:
            if m.received_at is not None:
                stamps.append((_EGRESS_DWELL_STAT, now - m.received_at))
                m.received_at = None
        return stamps

    def _recycle_responses(self, msgs: list) -> None:
        """Encode-then-recycle: an outbound RESPONSE envelope is dead
        the moment its bytes exist — nothing silo-side holds it past
        the wire (requests stay out: the sender's callback machinery
        owns them until correlation). One sweep per batch, shard-side
        (the freelist release is thread-safe; see core.message)."""
        dead = [m for m in msgs if m.direction is Direction.RESPONSE]
        if dead:
            recycle_messages(dead)
            self.recycled += len(dead)

    def _close_endpoint(self, endpoint: str) -> None:
        s = self._senders.pop(endpoint, None)
        if s is not None:
            # the backpressure entry dies with the sender: whatever it
            # never drained must not count against a re-dialed sender
            # to the same endpoint (the in-flight batch's decrement
            # no-ops on the missing key — see _Sender._run)
            self.pending.pop(endpoint, None)
            s.close()

    def _discard_ring(self) -> None:
        """Teardown fallback for a DEAD shard loop (callable from the
        main loop): sweep the ring WITHOUT running ``_drain`` — peer
        items would lazily build senders on the calling loop (dialing
        peers mid-shutdown, their tasks registered nowhere) and client
        items would ``create_task`` on the dead loop. Recycle the dead
        response envelopes instead; pushed == drained still holds."""
        def _recycle(item):
            payload = item[3]
            self._recycle_responses(
                payload if type(payload) is list else [payload])
        self.ring.discard(_recycle)

    async def flush_and_close(self) -> None:
        """Clean-shutdown drain, ON the shard loop: sweep the ring
        (consumer side — pushed == drained afterwards, the producers
        already stopped), let each sender flush its queue best-effort,
        then close the links."""
        self.ring.drain_now()
        for s in list(self._senders.values()):
            try:
                await s.drain_idle(2.0)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            s.close()
        self._senders.clear()


class EgressShardPool:
    """N egress shards for one fabric + the link-affinity assigner.
    Constructed by ``SocketFabric.register_silo`` when a local silo has
    ``egress_shards >= 1``: borrows the first N ingress shard loops when
    the silo runs multi-loop ingress (so a peer's outbound sender lives
    with the shard that owns the inbound half of the peering), else
    spawns N dedicated :class:`EgressLoopThread`\\ s. ``Silo.stop``
    closes it BEFORE the ingress pool and the message center so every
    accepted response still flushes — the clean-shutdown drain."""

    def __init__(self, fabric, silo: "Silo", n: int, ingress_pool=None):
        self.fabric = fabric
        self.owner = silo
        self.main_loop = asyncio.get_running_loop()
        self.closed = False
        self._rr = 0
        self._assigned: dict = {}   # endpoint -> shard index (stable)
        self._threads: list[EgressLoopThread] = []
        if ingress_pool is not None:
            self.on_ingress = True
            loops = [s.loop for s in
                     ingress_pool.shards[:max(1, min(n, len(
                         ingress_pool.shards)))]]
            if len(loops) < n:
                log.warning(
                    "egress_shards=%d capped at %d: egress shards "
                    "borrow the ingress loops (ingress_loops=%d) — "
                    "raise ingress_loops to get more egress shards",
                    n, len(loops), len(ingress_pool.shards))
        else:
            self.on_ingress = False
            cfg = silo.config
            pcfg = cfg if cfg.profiling_enabled else None
            self._threads = [
                EgressLoopThread(f"{cfg.name}-egress-{i}", pcfg)
                for i in range(n)]
            for t in self._threads:
                t.start()
            for t in self._threads:
                t._ready.wait(5.0)
            loops = [t.loop for t in self._threads]
        self.shards = [EgressShard(self, i, lp)
                       for i, lp in enumerate(loops)]

    # -- main-loop surface ----------------------------------------------
    def shard_for(self, endpoint: str) -> EgressShard:
        """Stable shard assignment for one peer endpoint: the ingress
        shard owning the inbound half of the peering when known (the
        handshake records it), else round-robin — and sticky either
        way, so one endpoint's traffic keeps per-target FIFO."""
        idx = self._assigned.get(endpoint)
        if idx is None:
            idx = None if not self.on_ingress else \
                self.fabric._peer_shard.get(endpoint)
            if idx is None or idx >= len(self.shards):
                idx = self._rr
                self._rr = (self._rr + 1) % len(self.shards)
            self._assigned[endpoint] = idx
        return self.shards[idx]

    def shard_for_client(self, addr) -> EgressShard:
        """Sticky shard for one CLIENT route (the multi-loop residue
        fix): under ``ingress_loops=1`` client connections are accepted
        on the main loop, so without this their response encodes ran
        there too while silo-peer links already encoded on the shards.
        Round-robin at registration, sticky for the connection's life —
        per-client FIFO holds exactly like per-peer FIFO does."""
        idx = self._assigned.get(addr)
        if idx is None:
            idx = self._rr
            self._rr = (self._rr + 1) % len(self.shards)
            self._assigned[addr] = idx
        return self.shards[idx]

    def _apply_stats(self, item) -> None:
        """Stat-ring drain (MAIN loop — the only thread the registry
        tolerates): replay the shard-stamped dwell/encode observations
        and the wire-byte ledger charges. The ledger entries are NOT
        metrics-gated — ledger-only silos stamp too."""
        est = self.fabric.egress_stats
        led = self.fabric.ledger
        for name, value in item[1]:
            if name is _LEDGER_WIRE:
                if led is not None:
                    route, nbytes = value
                    led.charge_wire(route, tx=nbytes)
            elif est is not None:
                est.observe(name, value)

    # -- lifecycle -------------------------------------------------------
    async def aclose(self) -> None:
        """Close + drain: new sends fall back to the classic main-loop
        path the moment ``closed`` flips (checked by every feed), the
        fabric detaches its shard sender handles, then each shard
        flushes on its own loop (ring swept, sender queues drained
        best-effort) and standalone threads join.

        Teardown ordering caveat (deliberate): a send issued DURING the
        bounded (5s) shard flush builds a fresh classic sender whose
        write can overtake messages the shard sender still holds —
        per-target FIFO is relaxed for that stop window only. The
        alternative (route feeds through each shard sender until it
        quiesces) cannot terminate under sustained load, which is
        exactly when ``Silo.stop`` runs this drain; responses are
        correlation-matched so the RPC layer is order-insensitive, and
        the window is bounded by the flush timeout."""
        if self.closed:
            return
        self.closed = True
        self.fabric._detach_shard_senders()
        loop = asyncio.get_running_loop()

        async def _flush(shard) -> None:
            alive = (self.on_ingress or
                     self._threads[shard.index].is_alive())
            if not alive:
                # loop dead: recycle the ring's envelopes — running the
                # drain here would build senders on THIS loop and write
                # on the dead one (see _discard_ring)
                shard._discard_ring()
                return
            try:
                await asyncio.wait_for(
                    asyncio.wrap_future(asyncio.run_coroutine_threadsafe(
                        shard.flush_and_close(), shard.loop)), 5.0)
            except Exception:  # noqa: BLE001 — wedged shard: say so
                log.warning("egress shard %d did not flush within 5s",
                            shard.index)

        # concurrent: the flushes are independent (each on its own
        # loop), so the whole drain is bounded by ONE flush timeout,
        # not shards x timeout
        await asyncio.gather(*(_flush(s) for s in self.shards))
        for t in self._threads:
            t.stop()
        for t in self._threads:
            if t.is_alive():
                await loop.run_in_executor(None, t.join, 5.0)
