"""Multi-process silos (ISSUE 18): SO_REUSEPORT worker processes +
shared-memory device staging rings.

``SiloConfig.worker_procs = N`` (N >= 2) forks N single-GIL worker
processes at ``Silo.start()``. The topology:

- **One advertised endpoint, N accepting processes.** The owner binds
  the advertised gateway port with ``SO_REUSEPORT`` at construction
  time (so the port is reserved and printable before start); each
  forked worker binds its OWN fresh ``SO_REUSEPORT`` listener to the
  same port and the kernel balances accepted connections across them.
  A connection pins to its accepting worker for life, so the multiloop
  FIFO argument carries over verbatim: senders hash grains to
  connections, per-grain FIFO is preserved with zero cross-process
  hops on the host-tier hot path, and host activations live in the
  accepting worker. The owner closes its own (never-accepting) copy of
  the listener once every worker reports ready — from then on the
  owner process serves NO client ingress at all (main-process pump +
  encode share -> ~0, the structural signal ``test_floor_multiproc``
  asserts).

- **Workers are full cluster members.** Each worker builds a real
  ``Silo`` on its own internal endpoint and joins the cluster through
  the shared file/sqlite membership table, so death detection
  (SIGKILL -> probes -> declared dead), directory convergence, and the
  per-silo ``ctl_*`` management surface all reuse the existing
  machinery unchanged — a worker is just a silo that happens to share
  the advertised gateway port.

- **One device engine.** Only the owner process owns jax and the
  ``VectorRuntime``; forked children never touch the device. Workers
  feed vector calls through cross-process SPSC staging rings built on
  ``multiprocessing.shared_memory`` (:class:`ShmRing` — the
  ``runtime/multiloop.py`` ring discipline one address space wider:
  single-writer cumulative counters on separate cache lines, pipe-byte
  wakeups coalesced exactly like the armed flag, message-bounded
  backpressure). The worker-side fill packs each ingress batch's calls
  column-major straight into the shared segment; the owner drains into
  ``VectorRuntime.call_packed`` (one method/table resolution per
  group, the ``call_group`` discipline) and the existing off-loop tick
  worker + tick fence claim/tick/resolve. Completions ride per-worker
  response rings back and resolve the worker-side futures on the
  worker's loop.

  Deliberate non-goal: the worker does NOT scatter into the engine's
  ``[n_shards, B, ...]`` staging buffers directly — lane allocation is
  owner state under the tick fence (slot lookup, conflict deferral,
  double-buffer rotation), and exporting the fence across processes
  would serialize exactly the work the rings decouple. The shared
  segment carries the columnar batch; the owner's staging fill stays
  where the fence lives.

- **Client-route relays.** Client pseudo-addresses share the
  advertised endpoint, so a response produced in a process that does
  NOT hold the client's connection cannot just dial the endpoint (the
  kernel would hand the connection to an arbitrary worker). Each
  worker announces its client routes to the owner over the request
  ring (``route+``/``route-``); the owner keeps
  ``fabric.route_relays`` (pseudo-address -> owning worker's internal
  endpoint) and relays; workers alias the advertised endpoint to the
  owner's internal endpoint (``fabric.endpoint_aliases``). Relay hops
  are bounded by the message forward count; an unroutable
  advertised-endpoint target is dropped with a log, never dialed.

``worker_procs = 1`` (the default) constructs none of this — today's
single-process path bit for bit (the A/B lever).
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import signal
import socket
import struct
import time
from typing import TYPE_CHECKING, Any

from ..core import serialization as _ser
from ..core.errors import ConfigurationError, SiloUnavailableError
from ..core.ids import SiloAddress
from ..observability.stats import COUNT_BOUNDS, RING_STATS

if TYPE_CHECKING:
    from .silo import Silo

log = logging.getLogger("orleans.multiproc")

# ring-stage metric names resolved once (observability.stats.RING_STATS —
# the cross-process leg of the ingest decomposition). Dwell stages are
# stamped push-side INTO the ring record (plain bytes cross the process
# boundary, the stamp-and-replay discipline one address space wider) and
# observed pop-side on the consumer's own loop; CLOCK_MONOTONIC is
# system-wide on Linux, so a producer stamp compares against a consumer
# read directly.
_RS_STAGING = RING_STATS["staging_dwell"]
_RS_RESPONSE = RING_STATS["response_dwell"]
_RS_DRAIN = RING_STATS["drain_batch"]
_RS_GROUP = RING_STATS["group"]
_RS_HOPS = RING_STATS["hops"]
_RS_RECORDS = RING_STATS["records"]

__all__ = ["ShmRing", "WorkerSupervisor", "VectorShmClient"]

# native ring primitives (hotwire.c shm_push/shm_pop operate on the
# identical layout, so a native producer and a pure-Python consumer
# interoperate — the ORLEANS_TPU_NATIVE=0 contract)
_HW = _ser._hotwire
_HW_SHM = _HW is not None and hasattr(_HW, "shm_push")

# ---------------------------------------------------------------------------
# ShmRing: the multiloop SpscRing discipline, one address space wider
# ---------------------------------------------------------------------------
# Header layout (all u64 little-endian):
#   [0:8]    write_cum    cumulative bytes written   (producer-only writer)
#   [8:16]   pushed_msgs  cumulative messages pushed (producer-only writer)
#   [64:72]  read_cum     cumulative bytes consumed  (consumer-only writer)
#   [72:80]  drained_msgs cumulative messages drained(consumer-only writer)
#   [128:]   data region (capacity bytes, 8-aligned)
# Each counter has exactly ONE writer on its own cache line, so no
# read-modify-write ever races (the SpscRing pushed/drained rule); the
# other side only reads. Records are `u32 len | u32 n_msgs | payload`,
# padded to 8 bytes; a record never wraps — when the contiguous tail is
# too short the producer writes a u32 0xFFFFFFFF wrap marker and both
# sides skip to the region start. backlog = pushed - drained, exactly
# the multiloop message-bounded backpressure signal.
_HDR = 128
_OFF_WRITE = 0
_OFF_PUSHED = 8
_OFF_READ = 64
_OFF_DRAINED = 72
_WRAP = 0xFFFFFFFF
_U64 = struct.Struct("<Q")
_REC = struct.Struct("<II")
# ring capacity in MESSAGES before the producer refuses (the multiloop
# _RING_CAPACITY twin); byte capacity bounds independently
_RING_MSG_CAPACITY = 16384


class ShmRing:
    """Bounded cross-process SPSC byte ring over one shared-memory
    segment + a pipe-byte wakeup. ``push`` runs in the producer process
    only, ``pop``/``drain pipe`` in the consumer process only (the
    SpscRing single-producer/single-consumer contract across a process
    boundary). Payloads are opaque bytes; both sides of a silo are the
    same trust domain (forked from one process), so records carry plain
    pickle."""

    __slots__ = ("shm", "buf", "capacity", "wake_rfd", "wake_wfd")

    def __init__(self, shm, wake_rfd: int, wake_wfd: int):
        self.shm = shm
        self.buf = shm.buf
        self.capacity = (shm.size - _HDR) & ~7
        if self.capacity <= 64:
            raise ValueError(f"shm segment too small: {shm.size}")
        self.wake_rfd = wake_rfd
        self.wake_wfd = wake_wfd

    # -- counters (cross-process readable; single writer each) -----------
    def _load(self, off: int) -> int:
        return _U64.unpack_from(self.buf, off)[0]

    def _store(self, off: int, v: int) -> None:
        # an aligned 8-byte store (single memcpy under CPython); the
        # native path uses release/acquire atomics for the same slot
        _U64.pack_into(self.buf, off, v)

    @property
    def pushed_msgs(self) -> int:
        return self._load(_OFF_PUSHED)

    @property
    def drained_msgs(self) -> int:
        return self._load(_OFF_DRAINED)

    def backlog(self) -> int:
        return self.pushed_msgs - self.drained_msgs

    # -- producer side ----------------------------------------------------
    def push(self, payload: bytes, n_msgs: int = 1) -> bool:
        """Append one record and wake the consumer. False = over
        capacity (bytes or messages) — bounded backpressure, the caller
        decides (drop / fail futures / retry later). Never blocks."""
        if self.backlog() >= _RING_MSG_CAPACITY:
            return False
        if _HW_SHM:
            try:
                if not _HW.shm_push(self.buf, self.capacity, payload,
                                    n_msgs):
                    return False
            except ValueError:
                return False
        elif not self._push_py(payload, n_msgs):
            return False
        try:
            os.write(self.wake_wfd, b"\x01")
        except (BlockingIOError, InterruptedError):
            pass  # wakeup already pending — self-coalescing
        except OSError:
            pass  # consumer side gone; the reaper handles it
        return True

    def _push_py(self, payload: bytes, n_msgs: int) -> bool:
        cap = self.capacity
        wc = self._load(_OFF_WRITE)
        rc = self._load(_OFF_READ)
        ln = len(payload)
        rec = 8 + ((ln + 7) & ~7)
        if rec > cap - 8:
            raise ValueError(f"record of {ln} bytes exceeds ring "
                             f"capacity {cap}")
        pos = wc % cap
        contig = cap - pos
        need = rec + (contig if contig < rec else 0)
        if cap - (wc - rc) < need:
            return False
        if contig < rec:
            # wrap marker, then restart at the region head (positions
            # stay 8-aligned, so the 4-byte marker always fits)
            _REC.pack_into(self.buf, _HDR + pos, _WRAP, 0)
            wc += contig
            pos = 0
        _REC.pack_into(self.buf, _HDR + pos, ln, n_msgs)
        self.buf[_HDR + pos + 8:_HDR + pos + 8 + ln] = payload
        # publish AFTER the payload bytes land (the release half; the
        # consumer's counter read is the acquire half)
        self._store(_OFF_WRITE, wc + rec)
        self._store(_OFF_PUSHED, self._load(_OFF_PUSHED) + n_msgs)
        return True

    # -- consumer side ----------------------------------------------------
    def drain_wakeups(self) -> None:
        """Clear pending wakeup bytes BEFORE popping (a push racing the
        drain either lands in this sweep or leaves a byte for the next
        wakeup — the armed-flag rule)."""
        try:
            while os.read(self.wake_rfd, 4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            pass

    def pop(self):
        """One record, or None when empty: ``(payload, n_msgs)``."""
        if _HW_SHM:
            return _HW.shm_pop(self.buf, self.capacity)
        return self._pop_py()

    def _pop_py(self):
        cap = self.capacity
        while True:
            rc = self._load(_OFF_READ)
            if self._load(_OFF_WRITE) == rc:
                return None
            pos = rc % cap
            ln, n_msgs = _REC.unpack_from(self.buf, _HDR + pos)
            if ln == _WRAP:
                self._store(_OFF_READ, rc + (cap - pos))
                continue
            payload = bytes(self.buf[_HDR + pos + 8:_HDR + pos + 8 + ln])
            self._store(_OFF_READ, rc + 8 + ((ln + 7) & ~7))
            self._store(_OFF_DRAINED, self._load(_OFF_DRAINED) + n_msgs)
            return payload, n_msgs

    def close(self) -> None:
        self.buf = None  # release the exported memoryview before shm close
        for fd in (self.wake_rfd, self.wake_wfd):
            try:
                os.close(fd)
            except OSError:
                pass
        try:
            self.shm.close()
        except (OSError, BufferError):
            pass


def _make_ring(size: int) -> ShmRing:
    from multiprocessing import shared_memory
    shm = shared_memory.SharedMemory(create=True, size=_HDR + size)
    shm.buf[:_HDR] = b"\x00" * _HDR
    r, w = os.pipe()
    os.set_blocking(r, False)
    os.set_blocking(w, False)
    return ShmRing(shm, r, w)


def _reuseport_listener(host: str, port: int = 0) -> socket.socket:
    """A fresh listening socket in the advertised endpoint's
    SO_REUSEPORT group (every member sets the option BEFORE bind — the
    kernel's admission rule). Native ``bind_reuseport`` when available
    (one syscall sequence, the hotwire ring's C twin), else the
    portable setsockopt path."""
    if _HW is not None and hasattr(_HW, "bind_reuseport"):
        fd = _HW.bind_reuseport(host, port)
        sock = socket.socket(fileno=fd)
    else:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        sock.bind((host, port))
        sock.listen(128)
    sock.setblocking(False)
    return sock


# ---------------------------------------------------------------------------
# Worker-side vector proxy: silo.vector in a worker process
# ---------------------------------------------------------------------------

class _ProxyTable:
    """Route-recording stand-in for ``ShardedActorTable`` at a worker:
    ``note_route`` collects (key_hash, uniform_hash) pairs that ride
    the next packed record to the owner's real table
    (``note_route_many``) — the ownership-sweep bookkeeping crosses the
    ring with the calls it belongs to."""

    __slots__ = ("routes",)

    def __init__(self) -> None:
        self.routes: list = []

    def note_route(self, key_hash: int, uniform_hash: int) -> None:
        if key_hash != uniform_hash:
            self.routes.append((key_hash, uniform_hash))

    def drain_routes(self) -> list:
        r, self.routes = self.routes, []
        return r


class VectorShmClient:
    """The worker process's ``silo.vector``: same call surface the
    dispatcher drives (``key_hash_for`` / ``table`` / ``call`` /
    ``call_group``), implemented as a packed push onto the
    cross-process staging ring. The dispatcher bypasses ring-ownership
    forwarding when this proxy is installed (``is_shm_proxy``): the
    ring IS the route — every vector call from this process funnels
    into the single owner-process engine, so all processes resolve a
    key to the same device row (the single-activation constraint,
    enforced by topology instead of per-message forwards)."""

    is_shm_proxy = True

    def __init__(self, ring_out: ShmRing, owner_address: SiloAddress):
        self.ring = ring_out
        self.owner_address = owner_address
        self._tables: dict[type, _ProxyTable] = {}
        self._futures: dict[int, asyncio.Future] = {}
        self._corr = 0
        # counters mirrored from the engine surface (samplers/ctl read
        # them through getattr guards)
        self.ticks = 0
        self.messages_processed = 0
        self.conflicts_deferred = 0
        self.exchange_lanes = 0
        self.tables: dict = {}
        self.pending: dict = {}
        # observability taps, set by _worker_async after the worker silo
        # builds them (None = off; every site guards on the None):
        # tracer closes the response-ring leg span per traced call,
        # stats is the silo's metrics-gated registry (ingest_stats
        # idiom) for the ring-stage histograms
        self.tracer = None
        self.stats = None
        # corr -> (trace_id, parent_span_id) for in-flight traced calls:
        # the response pop closes the return-leg span into the right
        # trace (bounded by the futures table it parallels)
        self._trace_of: dict[int, tuple] = {}

    # the one key->hash rule, mirrored from VectorRuntime.key_hash_for
    # (dispatch.engine imports jax; a worker process must not)
    @staticmethod
    def key_hash_for(key, uniform_hash: int) -> int:
        if isinstance(key, int) and 0 <= key < 2**62:
            return key
        return uniform_hash

    def table(self, cls: type) -> _ProxyTable:
        t = self._tables.get(cls)
        if t is None:
            t = self._tables[cls] = _ProxyTable()
        return t

    def queue_depth(self) -> int:
        return len(self._futures)

    def shutdown_worker(self) -> None:  # Silo.stop symmetry
        pass

    # -- the packed push --------------------------------------------------
    def call(self, grain_class: type, key_hash: int, method: str,
             **args) -> asyncio.Future:
        return self.call_group(grain_class, method,
                               [(key_hash, args, True)])[0]

    def call_group(self, grain_class: type, method: str,
                   items: list, traces: list | None = None,
                   origin: str | None = None) -> list:
        """Grouped enqueue, ring edition: the batch packs column-major
        (one names tuple + per-argument value columns — the staging
        layout the owner's ``call_packed`` consumes) and lands in the
        shared segment in ONE push. Returns one entry per item in item
        order: a future where ``want_future`` was set, else None (the
        ``call_group`` contract).

        ``traces`` (optional, parallel to ``items``) carries per-item
        ``(trace_id, parent_span_id)`` contexts: they ride the record
        across the ring so the owner opens correctly-parented ring-leg
        and device-tick child spans, and the response pop here closes
        the return leg. The record header carries the push stamps
        (monotonic for dwell, wall for span starts) and a relay hop
        count — stamped push-side, observed pop-side. ``origin`` is
        accepted for engine-signature parity; the owner labels batches
        by link, so it is unused here."""
        loop = asyncio.get_running_loop()
        tracer = self.tracer
        futs: list = []
        # sub-batches keyed by the kwargs name tuple: schema-bound
        # callers all share one; a mixed group still packs correctly
        subs: dict[tuple, list] = {}
        idx = -1
        for key_hash, args, want_future in items:
            idx += 1
            fut = loop.create_future() if want_future else None
            futs.append(fut)
            corr = -1
            if fut is not None:
                self._corr += 1
                corr = self._corr
                self._futures[corr] = fut
            tr = traces[idx] if traces is not None else None
            if tr is not None and tracer is not None:
                if corr >= 0:
                    self._trace_of[corr] = tr
                # this trace's legs are about to leave the process over
                # the ring — retention must fan the pull out (the
                # send-side hook rule, ring edition)
                tracer.mark_remote(tr[0])
            names = tuple(args)
            sub = subs.get(names)
            if sub is None:
                sub = subs[names] = [[], [], [list() for _ in names], []]
            sub[0].append(key_hash)
            sub[1].append(corr)
            for col, name in zip(sub[2], names):
                col.append(args[name])
            sub[3].append(tr)
        routes = self.table(grain_class).drain_routes()
        record = ("vec", grain_class.__name__, method, routes,
                  [(names, khs, corrs, cols, trs)
                   for names, (khs, corrs, cols, trs) in subs.items()],
                  time.monotonic(), time.time(), 1)
        if not self.ring.push(pickle.dumps(record, protocol=5),
                              n_msgs=len(items)):
            # bounded backpressure: the staging ring (or the engine
            # behind it) is saturated — fail promptly, like the egress
            # ring drop policy, instead of buffering without bound
            err = SiloUnavailableError(
                "device staging ring full (owner engine saturated)")
            for fut in futs:
                if fut is not None and not fut.done():
                    fut.set_exception(err)
            self._futures = {c: f for c, f in self._futures.items()
                             if not f.done()}
            self._trace_of = {c: t for c, t in self._trace_of.items()
                              if c in self._futures}
        return futs

    # -- response-ring drain (worker loop) --------------------------------
    def resolve(self, results: list, t_push_mono: float = 0.0,
                t_push_wall: float = 0.0) -> None:
        """Apply one response batch: ``(corr, ok, payload)`` triples.
        ``t_push_mono``/``t_push_wall`` are the owner's response-ring
        push stamps: the pop here (this worker's loop) closes the
        return-leg dwell — the response_dwell histogram plus one "ring"
        span per traced call, parented into the request's trace."""
        dwell = 0.0
        if t_push_mono:
            dwell = max(0.0, time.monotonic() - t_push_mono)
            st = self.stats
            if st is not None:
                st.observe(_RS_RESPONSE, dwell)
        tracer = self.tracer
        trace_of = self._trace_of
        futures = self._futures
        for corr, ok, payload in results:
            tr = trace_of.pop(corr, None)
            if tr is not None and tracer is not None and t_push_mono:
                tracer.record(tr[0], tr[1], "shm.response_ring", "ring",
                              t_push_wall, dwell, pid=os.getpid())
            fut = futures.pop(corr, None)
            if fut is None or fut.done():
                continue
            if ok:
                fut.set_result(payload)
            else:
                fut.set_exception(payload)

    def fail_all(self, exc: Exception) -> None:
        futs, self._futures = self._futures, {}
        self._trace_of.clear()
        for fut in futs.values():
            if not fut.done():
                fut.set_exception(exc)


# ---------------------------------------------------------------------------
# Boot plumbing (fork context: arguments pass by reference, unpickled)
# ---------------------------------------------------------------------------

class _WorkerBoot:
    """Everything one forked worker needs, captured before fork. Plain
    references — the fork start method never pickles, so test-local
    grain classes and closures cross intact."""

    __slots__ = ("index", "name", "host", "advertised_port",
                 "advertised_ep", "owner_internal_ep", "owner_address",
                 "config", "registry", "storage_providers",
                 "vector_interfaces", "membership_factory",
                 "req_ring", "resp_ring", "close_fds", "close_socks",
                 "management")

    def __init__(self, **kw) -> None:
        for k, v in kw.items():
            setattr(self, k, v)


class _WorkerLink:
    """Owner-side handle for one worker: process + both rings + the
    response batcher (single armed flush, the SpscRing wakeup rule on
    the outbound side too)."""

    __slots__ = ("index", "proc", "req_ring", "resp_ring", "silo_address",
                 "internal_ep", "ready", "dead", "out", "_flush_armed",
                 "origin")

    def __init__(self, index: int, proc, req_ring: ShmRing,
                 resp_ring: ShmRing, ready: asyncio.Future):
        self.index = index
        self.proc = proc
        self.req_ring = req_ring    # worker -> owner (consumer here)
        self.resp_ring = resp_ring  # owner -> worker (producer here)
        self.silo_address: SiloAddress | None = None
        self.internal_ep: str | None = None
        self.ready = ready
        self.dead = False
        self.out: list = []          # pending (corr, ok, payload)
        self._flush_armed = False
        # ledger attribution label for work this worker originates
        # (device row-seconds via _Pending.origin, wire bytes via
        # charge_wire) — the cross-process burner key
        self.origin = f"worker-{index}"


class WorkerSupervisor:
    """Owner-side lifecycle + shm engine server for the worker fleet:
    forks the workers, waits for their ready handshakes, closes the
    owner's never-accepting advertised listener, drains each request
    ring into the device engine, batches completions onto the response
    rings, maintains the client-route relay table, and reaps dead
    workers (SIGKILL mid-traffic: the ring goes quiet, membership
    probes declare the worker's silo dead, and the relays toward it
    drop here)."""

    # staging ring: sized for bursts of packed columnar batches;
    # response ring smaller (results are compact)
    REQ_RING_BYTES = 4 << 20
    RESP_RING_BYTES = 2 << 20

    def __init__(self, silo: "Silo"):
        self.silo = silo
        self.fabric = silo.fabric
        self.n = silo.config.worker_procs
        self.links: list[_WorkerLink] = []
        self.loop: asyncio.AbstractEventLoop | None = None
        self._reaper: asyncio.Task | None = None
        self._closed = False
        self._advertised_sock: socket.socket | None = None
        self._mbr_tmp: str | None = None

    # -- fork (owner, pre-services) ---------------------------------------
    def fork_workers(self) -> None:
        """Fork the fleet. Runs FIRST in ``Silo.start()`` — before the
        owner starts loops/threads/services — so each child begins from
        a quiet interpreter (only the forking thread survives a fork;
        a child must never touch inherited jax/loop state, and the less
        of it exists, the less there is to avoid)."""
        import multiprocessing
        silo = self.silo
        adv = silo.advertised_address
        assert adv is not None
        self._advertised_sock = self.fabric._listen_socks.get(adv.endpoint)
        membership_factory = self._membership_factory()
        ctx = multiprocessing.get_context("fork")
        storage_providers = dict(silo.storage_manager.providers)
        close_socks: list = [self._advertised_sock,
                             self.fabric._listen_socks.get(
                                 silo.silo_address.endpoint)]
        close_fds: list[int] = []
        for i in range(self.n):
            req = _make_ring(self.REQ_RING_BYTES)
            resp = _make_ring(self.RESP_RING_BYTES)
            boot = _WorkerBoot(
                index=i, name=f"{silo.config.name}-w{i}",
                host=adv.host, advertised_port=adv.port,
                advertised_ep=adv.endpoint,
                owner_internal_ep=silo.silo_address.endpoint,
                owner_address=silo.silo_address,
                config=silo.config, registry=silo.registry,
                storage_providers=storage_providers,
                vector_interfaces=dict(silo.vector_interfaces),
                membership_factory=membership_factory,
                # workers of a managed silo install their own SiloControl
                # so cluster fan-outs (ctl_metrics / ctl_loop_profile /
                # ctl_critical_path) reach every process by silo address
                management=getattr(silo, "silo_control", None) is not None,
                req_ring=req, resp_ring=resp,
                # earlier workers' wakeup pipes: close in this child so
                # a dead sibling's pipe EOF semantics stay crisp
                close_fds=list(close_fds),
                close_socks=list(close_socks))
            proc = ctx.Process(target=_worker_main, args=(boot,),
                               name=boot.name, daemon=True)
            proc.start()
            close_fds.extend((req.wake_rfd, req.wake_wfd,
                              resp.wake_rfd, resp.wake_wfd))
            self.links.append(_WorkerLink(i, proc, req, resp,
                                          asyncio.get_running_loop()
                                          .create_future()))

    def _membership_factory(self):
        """A per-process constructor for the SHARED membership table.
        Workers must see the same rows the owner does; only a
        path-backed table can cross the fork (each process re-opens by
        path). No membership at all -> a private file table in a
        tempdir, created here and auto-joined by the owner too."""
        mbr = self.silo.membership
        if mbr is None:
            import tempfile
            from ..membership import FileMembershipTable, join_cluster
            self._mbr_tmp = tempfile.mkdtemp(prefix="orleans-mbr-")
            path = os.path.join(self._mbr_tmp, "membership.json")
            join_cluster(self.silo, FileMembershipTable(path))
            return lambda: FileMembershipTable(path)
        table = mbr.table
        cls = type(table)
        path = getattr(table, "path", None)
        if path is None or cls.__name__ == "InMemoryMembershipTable" or \
                path == ":memory:":
            raise ConfigurationError(
                f"worker_procs > 1 needs a path-backed membership table "
                f"shared across processes (File/SqliteMembershipTable); "
                f"got {cls.__name__}")
        return lambda: cls(path)

    # -- owner-loop attach / ready barrier --------------------------------
    def attach(self, loop: asyncio.AbstractEventLoop) -> None:
        self.loop = loop
        for link in self.links:
            loop.add_reader(link.req_ring.wake_rfd, self._drain_link, link)
        self._reaper = loop.create_task(self._reap_loop())

    async def wait_ready(self, timeout: float = 30.0) -> None:
        """Block until every worker's silo is serving on its reuseport
        listener, then retire the owner's advertised-listener copy:
        while any fd to the owner's listening socket stays open the
        socket keeps its SO_REUSEPORT share and black-holes the
        connections hashed to it (nobody accepts there). Children close
        their inherited copies at boot; this close is the last."""
        try:
            await asyncio.wait_for(
                asyncio.gather(*(lk.ready for lk in self.links)), timeout)
        except asyncio.TimeoutError:
            dead = [lk.index for lk in self.links if not lk.ready.done()]
            raise SiloUnavailableError(
                f"worker processes {dead} did not come up within "
                f"{timeout}s") from None
        adv_ep = self.silo.advertised_address.endpoint
        if self._advertised_sock is not None:
            self.fabric._listen_socks.pop(adv_ep, None)
            self._advertised_sock.close()
            self._advertised_sock = None
        log.info("silo %s: %d reuseport workers serving %s",
                 self.silo.config.name, self.n, adv_ep)

    # -- request-ring drain (owner loop) -----------------------------------
    def _drain_link(self, link: _WorkerLink) -> None:
        ring = link.req_ring
        ring.drain_wakeups()
        st = self.silo.ingest_stats
        led = self.silo.ledger
        n_recs = rx_bytes = 0
        while True:
            rec = ring.pop()
            if rec is None:
                break
            n_recs += 1
            rx_bytes += len(rec[0])
            try:
                payload = pickle.loads(rec[0])
                kind = payload[0]
                if kind == "vec":
                    self._handle_vec(link, payload)
                elif kind == "route+":
                    self.fabric.route_relays[payload[1]] = payload[2]
                elif kind == "route-":
                    if self.fabric.route_relays.get(payload[1]) == \
                            payload[2]:
                        self.fabric.route_relays.pop(payload[1], None)
                elif kind == "ready":
                    _, addr, internal_ep = payload
                    link.silo_address = addr
                    link.internal_ep = internal_ep
                    if not link.ready.done():
                        link.ready.set_result(None)
                else:
                    log.warning("unknown shm record kind %r from "
                                "worker %d", kind, link.index)
            except Exception:  # noqa: BLE001 — one record, not the link
                log.exception("shm request record failed (worker %d)",
                              link.index)
        if n_recs:
            if st is not None:
                # drain-batch size + record counter: one observe per
                # wakeup sweep, on the owner's own loop (loop-confined)
                st.histogram_with(_RS_DRAIN, COUNT_BOUNDS).observe(n_recs)
                st.increment(_RS_RECORDS, n_recs)
            if led is not None:
                # inbound wire bytes land on the originating worker's
                # route row — the cross-process get_cluster_ledger key
                led.charge_wire(link.origin, rx_bytes, 0)

    def _handle_vec(self, link: _WorkerLink, payload) -> None:
        """One packed vector batch -> the engine. The columnar
        sub-batches join via ``call_packed`` (one method/table
        resolution + one tick schedule per group — the call_group
        discipline), route notes land in the real table, and each
        wanted future's completion batches onto the response ring.

        The record tail carries the worker's push stamps and per-sub
        trace-context columns: the pop here closes the staging-ring
        dwell (histogram + one "ring" span per distinct traced request,
        parented into the request's trace), and the contexts thread
        into ``call_packed`` so the tick records correctly-parented
        device-tick child spans. ``link.origin`` labels every item for
        the ledger's per-worker device-time attribution."""
        _, iface, method, routes, subs, t_mono, t_wall, hops = payload
        silo = self.silo
        rt = silo.vector
        vcls = silo.vector_interfaces.get(iface)
        if rt is None or vcls is None:
            err = SiloUnavailableError(
                f"no device engine for {iface} in the owner process")
            for _names, _khs, corrs, _cols, _trs in subs:
                for corr in corrs:
                    if corr >= 0:
                        self._complete_value(link, corr, False, err)
            return
        st = silo.ingest_stats
        dwell = max(0.0, time.monotonic() - t_mono)
        if st is not None:
            st.observe(_RS_STAGING, dwell)
            st.histogram_with(_RS_HOPS, COUNT_BOUNDS).observe(hops)
        tracer = silo.tracer
        if tracer is not None:
            seen: set = set()
            for _names, _khs, _corrs, _cols, trs in subs:
                for tr in trs:
                    if tr is None or tr in seen:
                        continue
                    seen.add(tr)
                    tracer.record(tr[0], tr[1], "shm.staging_ring",
                                  "ring", t_wall, dwell,
                                  worker=link.index)
        if routes:
            rt.table(vcls).note_route_many(routes)
        origin = link.origin if silo.ledger is not None else None
        for names, khs, corrs, cols, trs in subs:
            if st is not None:
                st.histogram_with(_RS_GROUP, COUNT_BOUNDS).observe(
                    len(khs))
            try:
                futs = rt.call_packed(vcls, method, khs,
                                      dict(zip(names, cols)),
                                      [c >= 0 for c in corrs],
                                      traces=(trs if tracer is not None
                                              else None),
                                      origin=origin)
            except Exception as e:  # noqa: BLE001 — unknown method etc.
                for corr in corrs:
                    if corr >= 0:
                        self._complete_value(link, corr, False, e)
                continue
            for corr, fut in zip(corrs, futs):
                if fut is not None:
                    fut.add_done_callback(
                        lambda f, lk=link, c=corr: self._complete(lk, c, f))

    # -- response batching (owner loop) ------------------------------------
    def _complete(self, link: _WorkerLink, corr: int, fut) -> None:
        if fut.cancelled():
            self._complete_value(link, corr, False, SiloUnavailableError(
                "device tick cancelled at silo stop"))
            return
        exc = fut.exception()
        if exc is not None:
            self._complete_value(link, corr, False, exc)
        else:
            self._complete_value(link, corr, True, fut.result())

    def _complete_value(self, link: _WorkerLink, corr: int, ok: bool,
                        payload) -> None:
        link.out.append((corr, ok, payload))
        if not link._flush_armed:
            link._flush_armed = True
            self.loop.call_soon(self._flush_link, link)

    def _flush_link(self, link: _WorkerLink) -> None:
        link._flush_armed = False
        if not link.out or link.dead:
            link.out.clear()
            return
        batch, link.out = link.out, []
        # push stamps ride the record (monotonic for the response-dwell
        # observe, wall for the return-leg span start — both closed by
        # the worker's pop); a retry re-stamps at its own push, so dwell
        # never absorbs the backoff
        stamps = (time.monotonic(), time.time())
        try:
            data = pickle.dumps(("res", batch) + stamps, protocol=5)
        except Exception:  # noqa: BLE001 — unpicklable result: per-item
            data = pickle.dumps(
                ("res", [self._portable(item) for item in batch])
                + stamps, protocol=5)
        if not link.resp_ring.push(data, n_msgs=len(batch)):
            # response ring full (worker loop stalled): hold the batch
            # and retry — results must not drop while the worker lives
            link.out = batch + link.out
            if not link._flush_armed:
                link._flush_armed = True
                self.loop.call_later(0.002, self._flush_link, link)
            return
        led = self.silo.ledger
        if led is not None:
            # outbound wire bytes join the worker's route row (the rx
            # half charges at the request-ring drain)
            led.charge_wire(link.origin, 0, len(data))

    @staticmethod
    def _portable(item):
        corr, ok, payload = item
        try:
            pickle.dumps(payload, protocol=5)
            return item
        except Exception as e:  # noqa: BLE001
            if ok:
                return (corr, False, SiloUnavailableError(
                    f"vector result could not cross the worker ring: {e}"))
            return (corr, False, SiloUnavailableError(
                f"vector error could not cross the worker ring: "
                f"{payload!r}"))

    # -- death watch --------------------------------------------------------
    async def _reap_loop(self) -> None:
        """A SIGKILLed worker goes silent: membership probes declare its
        SILO dead (directory convergence — existing machinery); this
        loop reaps the PROCESS — joins it, detaches its rings, and drops
        the client-route relays that pointed into it (those connections
        died with the process; senders learn via response timeout)."""
        while not self._closed:
            await asyncio.sleep(0.5)
            for link in self.links:
                if link.dead or link.proc.is_alive():
                    continue
                link.dead = True
                log.warning("worker process %d (pid %s) died",
                            link.index, link.proc.pid)
                self.loop.remove_reader(link.req_ring.wake_rfd)
                link.out.clear()
                if link.internal_ep is not None:
                    stale = [a for a, ep in
                             self.fabric.route_relays.items()
                             if ep == link.internal_ep]
                    for a in stale:
                        self.fabric.route_relays.pop(a, None)
                if not link.ready.done():
                    link.ready.set_exception(SiloUnavailableError(
                        f"worker {link.index} died during startup"))

    def alive_workers(self) -> int:
        return sum(1 for lk in self.links
                   if not lk.dead and lk.proc.is_alive())

    def describe(self) -> dict:
        """The ``ctl_workers`` payload: topology + per-worker ring
        counters (single-writer cumulative, so this read is torn-free)
        + the relay spread (client connections per worker — the accept
        balance the floor asserts on)."""
        relays: dict[str, int] = {}
        for ep in self.fabric.route_relays.values():
            relays[ep] = relays.get(ep, 0) + 1
        return {
            "worker_procs": self.n,
            "advertised": self.silo.advertised_address.endpoint,
            "workers": [{
                "index": lk.index,
                "pid": lk.proc.pid,
                "alive": (not lk.dead) and lk.proc.is_alive(),
                "silo": lk.internal_ep,
                "client_routes": relays.get(lk.internal_ep or "", 0),
                "req_pushed": lk.req_ring.pushed_msgs,
                "req_drained": lk.req_ring.drained_msgs,
                "req_backlog": lk.req_ring.backlog(),
                "resp_pushed": lk.resp_ring.pushed_msgs,
                "resp_drained": lk.resp_ring.drained_msgs,
                "resp_backlog": lk.resp_ring.backlog(),
            } for lk in self.links],
        }

    # -- shutdown ----------------------------------------------------------
    async def stop(self, graceful: bool = True) -> None:
        """Clean-shutdown drain: tell every live worker to stop (its
        silo drains its own rings/turns on its own loop), join the
        processes, take a FINAL sweep of each request ring (so every
        decoded-and-pushed record is accounted — pushed == drained
        afterwards), then unlink the segments."""
        if self._closed:
            return
        self._closed = True
        if self._reaper is not None:
            self._reaper.cancel()
        for link in self.links:
            self.loop.remove_reader(link.req_ring.wake_rfd)
            if not link.dead and link.proc.is_alive() and graceful:
                link.resp_ring.push(pickle.dumps(("stop",)), n_msgs=0)
        if graceful:
            deadline = time.monotonic() + 10.0
            loop = asyncio.get_running_loop()
            for link in self.links:
                budget = max(0.1, deadline - time.monotonic())
                await loop.run_in_executor(None, link.proc.join, budget)
        for link in self.links:
            if link.proc.is_alive():
                link.proc.terminate()
                await asyncio.get_running_loop().run_in_executor(
                    None, link.proc.join, 2.0)
            if link.proc.is_alive():
                link.proc.kill()
        for link in self.links:
            # final sweep: whatever the workers pushed before exiting
            # still routes (route-/vec records from their own drains)
            self._drain_link(link)
            # completions that land after this point have nowhere to go
            # (the worker is gone): _flush_link drops them on the flag
            link.dead = True
            link.req_ring.close()
            link.resp_ring.close()
            try:
                link.req_ring.shm.unlink()
                link.resp_ring.shm.unlink()
            except (OSError, FileNotFoundError):
                pass
        if self._advertised_sock is not None:
            self._advertised_sock.close()
            self._advertised_sock = None

    def cleanup_membership_dir(self) -> None:
        """Remove the auto-provisioned membership tempdir. Called by the
        silo AFTER its own membership oracle has shut down — the OWNER's
        iam-alive/refresh timers keep writing the table file past
        ``stop()`` (workers stop first by design), so removing it there
        would turn every later timer tick into a FileNotFoundError."""
        if self._mbr_tmp is not None:
            import shutil
            shutil.rmtree(self._mbr_tmp, ignore_errors=True)
            self._mbr_tmp = None


# ---------------------------------------------------------------------------
# Worker process body
# ---------------------------------------------------------------------------

def _worker_main(boot: _WorkerBoot) -> None:
    """Forked child entry: shed inherited resources, build THIS
    process's silo, serve. Exits via ``os._exit`` so the parent's
    atexit/pytest machinery never runs twice."""
    code = 0
    try:
        # inherited listener fds FIRST: while this child holds a copy
        # of the owner's advertised reuseport listener, that socket
        # keeps its accept share after the owner closes its own fd —
        # and nobody accepts there (the black-hole)
        for s in boot.close_socks:
            try:
                if s is not None:
                    s.close()
            except OSError:
                pass
        for fd in boot.close_fds:
            try:
                os.close(fd)
            except OSError:
                pass
        asyncio.run(_worker_async(boot))
    except Exception:  # noqa: BLE001 — the parent reads our stderr
        log.exception("worker %s crashed", boot.name)
        code = 1
    finally:
        os._exit(code)


async def _worker_async(boot: _WorkerBoot) -> None:
    from dataclasses import replace

    from ..membership import join_cluster
    from ..storage.core import StorageManager
    from .silo import Silo
    from .socket_fabric import SocketFabric

    loop = asyncio.get_running_loop()
    stop_ev = asyncio.Event()
    try:
        loop.add_signal_handler(signal.SIGTERM, stop_ev.set)
        loop.add_signal_handler(signal.SIGINT, stop_ev.set)
    except (NotImplementedError, RuntimeError):
        pass

    # worker_procs=1: a worker never forks its own fleet. The owner's
    # Prometheus endpoint is a TCP port WITHOUT SO_REUSEPORT — N workers
    # inheriting its number would collide at bind (or worse, silently
    # shadow each other) — so workers rebind ephemeral (port 0) when the
    # owner serves metrics at all, else stay serverless; per-process
    # metrics stay reachable over ctl (ctl_metrics / ctl_critical_path
    # fan out by silo address). Everything else — tracing, profiling
    # (flight-recorder triggers), ledger, SLO — inherits, so anomaly
    # triggers fire IN the worker that breached.
    cfg = replace(boot.config, name=boot.name, worker_procs=1,
                  metrics_port=(0 if boot.config.metrics_port is not None
                                else None))
    fabric = SocketFabric(boot.host)
    storage = StorageManager()
    storage.providers.update(boot.storage_providers)
    silo = Silo(cfg, fabric, boot.registry, storage)
    join_cluster(silo, boot.membership_factory())
    await silo.start()

    if getattr(boot, "management", False):
        # the owner runs add_management: mirror the SiloControl system
        # target here so ManagementGrain fan-outs (cluster metrics, loop
        # profiles, the critical-path waterfall) reach THIS process by
        # its silo address — workers are full cluster members
        from ..management.control import SILO_CONTROL, SiloControl
        control = SiloControl(silo)
        silo.register_system_target(control, SILO_CONTROL)
        silo.silo_control = control

    # the device proxy: every vector call from this process crosses the
    # staging ring into the owner's engine (installed before the
    # reuseport listener opens, so no client ever races it)
    proxy = None
    if boot.vector_interfaces:
        proxy = VectorShmClient(boot.req_ring, boot.owner_address)
        # observability taps: the proxy stamps trace contexts into ring
        # records and closes response-ring legs on THIS silo's collector
        # / metrics-gated registry (both None when the plane is off)
        proxy.tracer = silo.tracer
        proxy.stats = silo.ingest_stats
        silo.vector = proxy
        silo.vector_interfaces.update(boot.vector_interfaces)
    # responses to clients held by OTHER processes route via the owner
    fabric.endpoint_aliases[boot.advertised_ep] = boot.owner_internal_ep

    # client-route announcements -> the owner's relay table
    def _route_notify(addr, up: bool) -> None:
        kind = "route+" if up else "route-"
        boot.req_ring.push(
            pickle.dumps((kind, addr, silo.silo_address.endpoint)),
            n_msgs=0)
    fabric.route_notify = _route_notify

    # response-ring drain: resolve proxy futures on this loop
    def _drain_responses() -> None:
        ring = boot.resp_ring
        ring.drain_wakeups()
        while True:
            rec = ring.pop()
            if rec is None:
                return
            try:
                payload = pickle.loads(rec[0])
            except Exception:  # noqa: BLE001
                log.exception("bad response record")
                continue
            if payload[0] == "res":
                if proxy is not None:
                    proxy.resolve(payload[1], payload[2], payload[3])
            elif payload[0] == "stop":
                stop_ev.set()
    loop.add_reader(boot.resp_ring.wake_rfd, _drain_responses)

    # THIS process's membership of the advertised endpoint's reuseport
    # group: a fresh listener (never the inherited fd), accepted
    # connections pin here for life
    lsock = _reuseport_listener(boot.host, boot.advertised_port)
    server = await asyncio.start_server(
        lambda r, w: fabric._handle_conn(silo, r, w), sock=lsock)

    boot.req_ring.push(
        pickle.dumps(("ready",
                      (silo.silo_address.host, silo.silo_address.port,
                       silo.silo_address.generation),
                      silo.silo_address.endpoint)), n_msgs=0)
    log.info("worker %s serving %s (silo %s)", boot.name,
             boot.advertised_ep, silo.silo_address.endpoint)

    await stop_ev.wait()

    server.close()
    await server.wait_closed()
    loop.remove_reader(boot.resp_ring.wake_rfd)
    if proxy is not None:
        proxy.fail_all(SiloUnavailableError("worker stopping"))
    await silo.stop()
