"""Grain cancellation tokens: cooperative cancellation across grain calls.

Re-design of the reference's cancellation subsystem
(/root/reference/src/Orleans.Core.Abstractions/Cancellation/
GrainCancellationToken.cs:17 + GrainCancellationTokenSource.cs,
Orleans.Core/Runtime/GrainCancellationTokenRuntime.cs:12, and the
activation-side registry Orleans.Runtime/Cancellation/
CancellationSourcesExtension.cs:14) on asyncio primitives:

* a :class:`GrainCancellationToken` wraps an ``asyncio.Event``; grain code
  observes it cooperatively (``token.is_cancelled`` / ``await
  token.wait()``) — cancellation never hard-kills a turn, matching the
  reference's CancellationToken semantics;
* passing a token as a call argument records the target grain on the
  token (the reference's ``_targetGrainReferences``), and in-silo calls
  share the token OBJECT (identity deep-copier), so a local cancel fires
  instantly with zero messaging;
* across the wire the token travels as ``(id, cancelled)`` and the
  receiving silo interns a twin per id (CancellationSourcesExtension's
  interner), so every activation handed the same token id observes one
  shared event;
* :meth:`GrainCancellationTokenSource.cancel` fires the local event and
  fans a ``__cancel_token__`` system call out to every recorded target
  grain — always-interleave, since the turn being cancelled is typically
  still running on the target's activation.
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from .silo import Silo

__all__ = ["GrainCancellationToken", "GrainCancellationTokenSource"]

CANCEL_METHOD = "__cancel_token__"


def _register_copier() -> None:
    # tokens are SHARED objects for in-silo calls (a local cancel must be
    # visible to the callee instantly): identity deep-copier, like the
    # frozen id types
    from ..core.serialization import register_copier
    register_copier(GrainCancellationToken, lambda t: t)


def _rebuild_token(token_id: str, cancelled: bool) -> "GrainCancellationToken":
    return GrainCancellationToken(token_id, cancelled)


class GrainCancellationToken:
    """Cooperative cancellation signal passed as a grain-call argument."""

    __slots__ = ("id", "_event", "_targets", "__weakref__")

    def __init__(self, token_id: str | None = None,
                 cancelled: bool = False):
        self.id = token_id or uuid.uuid4().hex
        self._event = asyncio.Event()
        if cancelled:
            self._event.set()
        # grain ids this token was passed to: (GrainId, grain class)
        # recorded at send time so cancel() can reach remote twins
        self._targets: dict = {}

    @property
    def is_cancelled(self) -> bool:
        return self._event.is_set()

    async def wait(self) -> None:
        """Suspend until the token is cancelled."""
        await self._event.wait()

    def _fire(self) -> None:
        self._event.set()

    def __reduce__(self):
        # wire form: id + state; the receiving silo interns a twin
        return (_rebuild_token, (self.id, self.is_cancelled))

    def __repr__(self) -> str:
        state = "cancelled" if self.is_cancelled else "live"
        return f"GrainCancellationToken({self.id[:8]}, {state})"


class GrainCancellationTokenSource:
    """Creator/canceller of one token (GrainCancellationTokenSource.cs)."""

    def __init__(self) -> None:
        self.token = GrainCancellationToken()

    async def cancel(self) -> None:
        """Fire the token locally and notify every remote grain the token
        was passed to (best effort, gathered; a target that cannot be
        reached will still observe the flag if the call retries there)."""
        self.token._fire()
        notifies = []
        for gid, (client, cls) in list(self.token._targets.items()):
            try:
                fut = client.send_request(
                    target_grain=gid, grain_class=cls,
                    interface_name=cls.__name__ if cls else "",
                    method_name=CANCEL_METHOD, args=(self.token.id,),
                    kwargs={}, is_always_interleave=True)
            except Exception:  # noqa: BLE001 — best effort per target: a
                continue       # raising transmit must not skip the rest
            if fut is not None:
                notifies.append(fut)
        if notifies:
            await asyncio.gather(*notifies, return_exceptions=True)

    def dispose(self) -> None:
        self.token._targets.clear()


# ---------------------------------------------------------------------------
# Silo-side interner (CancellationSourcesExtension.cs:14): one twin per
# token id, so every activation handed the same id observes one event.
# ---------------------------------------------------------------------------

_PRECANCELLED_TTL = 300.0
_PRECANCELLED_CAP = 4096


class TokenInterner:
    """Per-silo token registry.

    Live twins are held WEAKLY: whatever grain/turn holds the token keeps
    the entry alive, and an entry disappears exactly when no one can
    observe it anymore — a TTL sweep could otherwise evict a twin a
    long-running turn is still awaiting, silently losing its cancel.
    Pre-cancelled twins (a ``__cancel_token__`` that arrived before or
    without the token itself) are held STRONGLY with a TTL + cap, since
    nothing references them yet."""

    def __init__(self, silo: "Silo | None" = None) -> None:
        import weakref
        self._silo = silo
        self._twins: "weakref.WeakValueDictionary[str, GrainCancellationToken]" = \
            weakref.WeakValueDictionary()
        self._precancelled: dict[str, tuple[GrainCancellationToken, float]] = {}

    def intern(self, token: GrainCancellationToken) -> GrainCancellationToken:
        twin = self._twins.get(token.id)
        if twin is not None:
            if token.is_cancelled:
                self.fire(token.id)
            return twin
        if self._precancelled.pop(token.id, None) is not None:
            token._fire()  # cancel raced ahead of the call
        self._twins[token.id] = token
        return token

    def fire(self, token_id: str) -> bool:
        twin = self._twins.get(token_id)
        if twin is None:
            # cancel arrived before (or without) the token itself: keep a
            # pre-cancelled twin so a late-delivered call still sees it
            # (capped: cancel-first floods must not grow without bound)
            if token_id not in self._precancelled:
                now = time.monotonic()
                if len(self._precancelled) >= _PRECANCELLED_CAP:
                    self._sweep(now)
                    while len(self._precancelled) >= _PRECANCELLED_CAP:
                        # TTL freed nothing (cancel-first flood inside the
                        # window): evict oldest — the cap is a hard bound
                        oldest = min(self._precancelled,
                                     key=lambda t: self._precancelled[t][1])
                        self._precancelled.pop(oldest)
                self._precancelled[token_id] = (
                    GrainCancellationToken(token_id, cancelled=True), now)
            return False
        if twin.is_cancelled:
            return True  # already fired + cascaded
        twin._fire()
        # cascade: a remote grain may have FORWARDED this token onward —
        # its targets were recorded on our twin by register_outgoing_tokens,
        # and only this silo knows about them (the source only knows its
        # own first hops). One-way, best-effort, loop-safe: a twin that is
        # already cancelled returns above without re-cascading.
        silo = self._silo
        if silo is not None:
            for gid, (client, cls) in list(twin._targets.items()):
                try:
                    client.send_request(
                        target_grain=gid, grain_class=cls,
                        interface_name=cls.__name__ if cls else "",
                        method_name=CANCEL_METHOD, args=(token_id,),
                        kwargs={}, is_always_interleave=True,
                        is_one_way=True)
                except Exception:  # noqa: BLE001 — best-effort fan-out
                    pass
        return True

    def _sweep(self, now: float) -> None:
        for tid, (_, touched) in list(self._precancelled.items()):
            if now - touched > _PRECANCELLED_TTL:
                self._precancelled.pop(tid, None)


def register_outgoing_tokens(client, grain_id, grain_class,
                             args: tuple, kwargs: dict) -> None:
    """Send-time hook: record the call target on every token argument so
    the source's cancel() can reach its remote twin."""
    for a in args:
        if type(a) is GrainCancellationToken:
            a._targets[grain_id] = (client, grain_class)
    if kwargs:
        for a in kwargs.values():
            if type(a) is GrainCancellationToken:
                a._targets[grain_id] = (client, grain_class)


def maybe_intern_tokens(silo: "Silo", args: tuple,
                        kwargs: dict) -> tuple[tuple, dict]:
    """Receive-time hook: swap decoded token twins for the silo's interned
    instance (shared event per id). Single pass with an early exit — this
    runs on every application invoke, and the overwhelmingly common case
    is no token at all. In-proc calls pass the original object (identity
    copier), for which interning is a registration no-op."""
    first = -1
    for i, a in enumerate(args):
        if type(a) is GrainCancellationToken:
            first = i
            break
    kw_hit = False
    if kwargs:
        for v in kwargs.values():
            if type(v) is GrainCancellationToken:
                kw_hit = True
                break
    if first < 0 and not kw_hit:
        return args, kwargs
    interner = silo.cancellation_tokens
    if first >= 0:
        args = tuple(
            interner.intern(a) if type(a) is GrainCancellationToken else a
            for a in args)
    if kw_hit:
        kwargs = {
            k: interner.intern(v) if type(v) is GrainCancellationToken else v
            for k, v in kwargs.items()}
    return args, kwargs


_register_copier()
