"""Catalog: activation lifecycle + local activation directory + idle GC.

Re-design of /root/reference/src/Orleans.Runtime/Catalog/Catalog.cs:26
(``GetOrCreateActivation:443-518``, ``InitActivation:540-576``, deactivation
:780-917), ``ActivationDirectory.cs`` (local map), and
``ActivationCollector.cs:15`` (idle GC, here a periodic sweep task instead of
a ticking wheel — activation counts per silo are far smaller than the
reference's because the million-actor tier lives in the vectorized tables of
orleans_tpu.dispatch, not in per-object activations).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import TYPE_CHECKING

from ..core.errors import InconsistentStateError, NonExistentActivationError
from ..core.ids import ActivationId, GrainId
from ..core.message import Message
from .activation import ActivationData, ActivationState
from .grain import StatefulGrain

if TYPE_CHECKING:
    from .silo import Silo

log = logging.getLogger("orleans.catalog")

DEFAULT_COLLECTION_AGE = 2 * 3600.0  # GrainCollectionOptions default (2h)
DEFAULT_COLLECTION_QUANTUM = 60.0


class Catalog:
    def __init__(self, silo: "Silo"):
        self.silo = silo
        # ActivationDirectory: local maps (ActivationDirectory.cs)
        self.by_activation: dict[ActivationId, ActivationData] = {}
        self.by_grain: dict[GrainId, list[ActivationData]] = {}
        self._collector_task: asyncio.Task | None = None
        self.collection_quantum = silo.config.collection_quantum
        self.deactivation_tasks: set[asyncio.Task] = set()

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._collector_task = asyncio.get_running_loop().create_task(
            self._collector_loop())

    async def stop(self) -> None:
        if self._collector_task:
            self._collector_task.cancel()
        # graceful: deactivate all activations (Silo stop path Silo.cs:663-802)
        acts = list(self.by_activation.values())
        await asyncio.gather(
            *(self._deactivate(a) for a in acts), return_exceptions=True)

    # ------------------------------------------------------------------
    # Get-or-create (GetOrCreateActivation:443-518)
    # ------------------------------------------------------------------
    def get_or_create_activation(self, msg: Message) -> ActivationData:
        grain_id = msg.target_grain
        # targeted at a specific activation? (response routing / forwarding)
        if msg.target_activation is not None:
            act = self.by_activation.get(msg.target_activation)
            if act is not None:
                return act
            # dead-target: the caller must re-address (NonExistentActivation)
            raise NonExistentActivationError(
                f"activation {msg.target_activation} not here")
        acts = self.by_grain.get(grain_id)
        if acts:
            if len(acts) == 1 and not self._is_stateless(acts[0].grain_class):
                return acts[0]
            # stateless worker: pick the least-loaded local replica; if all
            # are busy and the cap allows, scale out with a fresh replica
            # (StatelessWorkerDirector.cs:8 + StatelessWorkerPlacement max)
            def load(a: ActivationData) -> int:
                return (len(a.running) + len(a.waiting)
                        + len(a.activating_backlog))
            best = min(acts, key=load)
            cap = getattr(best.grain_class, "__orleans_stateless_worker__", 0)
            if load(best) > 0 and len(acts) < cap:
                return self._create_activation(grain_id, best.grain_class)
            return best
        grain_class = self.silo.registry.resolve(msg.interface_name)
        if grain_class is None:
            raise NonExistentActivationError(
                f"no grain class registered for {msg.interface_name!r}")
        if self._is_stateless(grain_class):
            return self._create_activation(grain_id, grain_class)
        # Single-activation grains: only create here if this silo is the
        # directory-designated host; otherwise the message was misrouted.
        if not self.silo.locator.should_host(grain_id, grain_class, msg):
            raise NonExistentActivationError(
                f"{grain_id} is not placed on this silo")
        return self._create_activation(grain_id, grain_class)

    def maybe_add_stateless_replica(self, grain_id: GrainId,
                                    grain_class: type) -> None:
        """StatelessWorker auto-scale: add a replica if all are busy and the
        local cap allows (StatelessWorkerPlacement max_local)."""
        cap = getattr(grain_class, "__orleans_stateless_worker__", 0)
        acts = self.by_grain.get(grain_id, [])
        if 0 < len(acts) < cap and all(a.running for a in acts):
            self._create_activation(grain_id, grain_class)

    def _is_stateless(self, grain_class: type) -> bool:
        return getattr(grain_class, "__orleans_stateless_worker__", 0) > 0

    def _create_activation(self, grain_id: GrainId,
                           grain_class: type) -> ActivationData:
        # warm the per-class invoker table at activation-class registration
        # (runtime.invoker): the first hot-lane call to this class must not
        # pay the build, and the build itself caches remote_methods on cls
        self.silo.invokers.entry(grain_class)
        act = ActivationData(grain_id, self.silo.runtime, grain_class,
                             max_enqueued=self.silo.config.max_enqueued_requests)
        act.state = ActivationState.ACTIVATING
        self.by_activation[act.activation_id] = act
        self.by_grain.setdefault(grain_id, []).append(act)
        asyncio.get_running_loop().create_task(self._init_activation(act))
        return act

    async def _init_activation(self, act: ActivationData) -> None:
        """InitActivation:540-576: register in the distributed directory,
        construct the grain, run on_activate, then drain the backlog."""
        try:
            if not act.is_stateless_worker and not act.grain_id.is_system_target():
                winner = await self.silo.locator.register(act.address)
                if winner is not None and winner.activation != act.activation_id:
                    # duplicate-activation race: another silo won
                    # (Catalog duplicate resolution) — forward backlog there.
                    self._destroy(act)
                    for m in act.activating_backlog:
                        m.target_silo = winner.silo
                        m.target_activation = None
                        self.silo.dispatcher.transmit(m)
                    act.activating_backlog.clear()
                    return
            instance = self.silo.registry.construct(act.grain_class)
            instance._activation = act
            act.grain_instance = instance
            if isinstance(instance, StatefulGrain):
                act.storage_bridge = self.silo.storage_manager.bridge_for(act)
                await instance.read_state()
            await self.silo.dispatcher_scoped(act, instance.on_activate)
            act.state = ActivationState.VALID
            self.silo.stats.increment("catalog.activations.created")
            backlog, act.activating_backlog = act.activating_backlog, type(act.activating_backlog)()
            for m in backlog:
                self.silo.dispatcher.receive_request(act, m)
        except Exception as e:  # noqa: BLE001 — init failure rejects backlog
            log.exception("activation init failed for %s", act.grain_id)
            self._destroy(act)
            from ..core.message import RejectionType
            for m in act.activating_backlog:
                self.silo.dispatcher._reject(
                    m, RejectionType.TRANSIENT, f"activation init failed: {e}")
            act.activating_backlog.clear()

    # ------------------------------------------------------------------
    # Live migration, inbound half (orleans_tpu.rebalance — the
    # reference's activation-repartitioning rehydrate: Orleans 7 grain
    # migration dehydrates state at the source and rehydrates here)
    # ------------------------------------------------------------------
    async def rehydrate_activation(self, grain_id: GrainId,
                                   grain_class: type, state_payload,
                                   prev_activation) -> ActivationData:
        """Create a VALID activation carrying migrated in-memory state.

        Mirrors ``_create_activation`` + ``_init_activation`` with three
        deltas: registration goes through the locator's migrate path
        (REPLACING the source's entry instead of losing first-wins to it);
        storage is still read first so the etag arms, but the migrated
        state overwrites the stored snapshot (the in-memory rows are newer
        than the last persisted write); and the method is awaited by the
        migration RPC, so the source only destroys its copy after this
        silo is serving. Raises on any failure — the source rolls back."""
        from ..core.errors import OrleansError

        if self.by_grain.get(grain_id):
            raise OrleansError(
                f"{grain_id} already has an activation on this silo")
        self.silo.invokers.entry(grain_class)  # warm the invoker table
        act = ActivationData(grain_id, self.silo.runtime, grain_class,
                             max_enqueued=self.silo.config.max_enqueued_requests)
        act.state = ActivationState.ACTIVATING
        self.by_activation[act.activation_id] = act
        self.by_grain.setdefault(grain_id, []).append(act)
        registered = False
        try:
            winner = await self.silo.locator.migrate_register(
                act.address, prev_activation)
            if winner is not None and \
                    winner.activation != act.activation_id:
                raise OrleansError(
                    f"migration of {grain_id} lost to a live "
                    f"registration on {winner.silo}")
            registered = True
            instance = self.silo.registry.construct(grain_class)
            instance._activation = act
            act.grain_instance = instance
            if isinstance(instance, StatefulGrain):
                act.storage_bridge = self.silo.storage_manager.bridge_for(act)
                await instance.read_state()  # arm the etag
                if state_payload is not None:
                    instance.state = state_payload
            await self.silo.dispatcher_scoped(act, instance.on_activate)
            act.state = ActivationState.VALID
            self.silo.stats.increment("catalog.activations.migrated_in")
        except BaseException:
            self._destroy(act)
            if registered:
                # surrender the claimed entry so the source's rollback
                # re-registration wins cleanly instead of losing
                # first-wins to our dead claim
                try:
                    await self.silo.locator.unregister(act.address)
                except Exception:  # noqa: BLE001 — stale-entry heal covers
                    pass
            # requests that raced in while we were ACTIVATING re-address
            # against the directory (which still/again names the source)
            for m in act.activating_backlog:
                m.target_silo = None
                m.target_activation = None
                self.silo.dispatcher.send_message(m)
            act.activating_backlog.clear()
            raise
        backlog, act.activating_backlog = \
            act.activating_backlog, type(act.activating_backlog)()
        for m in backlog:
            self.silo.dispatcher.receive_request(act, m)
        return act

    # ------------------------------------------------------------------
    # Deactivation (Catalog.cs:780-917)
    # ------------------------------------------------------------------
    def schedule_deactivation(self, act: ActivationData,
                              stuck: bool = False) -> None:
        t = asyncio.get_running_loop().create_task(
            self._deactivate(act, stuck=stuck))
        self.deactivation_tasks.add(t)
        t.add_done_callback(self.deactivation_tasks.discard)

    async def _deactivate(self, act: ActivationData,
                          stuck: bool = False) -> None:
        if act.state in (ActivationState.DEACTIVATING, ActivationState.INVALID):
            return
        act.state = ActivationState.DEACTIVATING
        act.stop_timers()
        if not stuck:  # stuck: no drain wait and no hook — both would hang
            # wait for running turns to drain (bounded)
            deadline = time.monotonic() + self.silo.config.deactivation_timeout
            while act.running and time.monotonic() < deadline:
                await asyncio.sleep(0.005)
            try:
                hook = getattr(act.grain_instance, "on_deactivate", None)
                if hook is not None:
                    await hook()
            except Exception:  # noqa: BLE001
                log.exception("on_deactivate failed for %s", act.grain_id)
        if not act.is_stateless_worker and not act.grain_id.is_system_target():
            try:
                await self.silo.locator.unregister(act.address)
            except Exception:  # noqa: BLE001
                log.exception("directory unregister failed for %s", act.grain_id)
        self._destroy(act)
        self.silo.stats.increment("catalog.activations.destroyed")
        # re-dispatch any stragglers: virtual-actor guarantee — next call
        # recreates elsewhere (Dispatcher forwarding semantics). Internal
        # turns (__timer__ ticks) die with the activation: re-dispatching
        # would resurrect it with a callback bound to the destroyed instance.
        for m in act.waiting:
            if m.method_name == "__timer__":
                _, done = m.body
                if done is not None and not done.done():
                    done.cancel()
                continue
            m.target_silo = None
            m.target_activation = None
            self.silo.dispatcher.send_message(m)
        act.waiting.clear()

    def on_invoke_error(self, act: ActivationData, exc: BaseException) -> None:
        """InconsistentStateException → deactivate so the next call rebuilds
        from storage (InsideRuntimeClient.cs:390-402)."""
        if isinstance(exc, InconsistentStateError):
            self.schedule_deactivation(act)

    def _destroy(self, act: ActivationData) -> None:
        act.state = ActivationState.INVALID
        act.stop_timers()
        self.by_activation.pop(act.activation_id, None)
        lst = self.by_grain.get(act.grain_id)
        if lst:
            try:
                lst.remove(act)
            except ValueError:
                pass
            if not lst:
                self.by_grain.pop(act.grain_id, None)

    # ------------------------------------------------------------------
    # Idle collection (ActivationCollector.cs:15)
    # ------------------------------------------------------------------
    async def _collector_loop(self) -> None:
        while True:
            await asyncio.sleep(self.collection_quantum * (0.9 + 0.2 * random.random()))
            now = time.monotonic()
            stuck_limit = self.silo.config.max_request_processing_time
            for act in list(self.by_activation.values()):
                if act.grain_id.is_system_target():
                    continue  # system targets live as long as the silo
                if act.state != ActivationState.VALID:
                    continue
                if not act.is_inactive:
                    # stuck-activation detection (DeactivateStuckActivation,
                    # ActivationData.cs:583-593, Catalog.cs:787): a turn
                    # exceeding the request-age limit will never finish —
                    # abandon the activation so the next call rebuilds it
                    # elsewhere (the hung coroutine is orphaned; its late
                    # response, if any, is dropped by the callback registry)
                    if act.oldest_running_age() > stuck_limit:
                        log.error(
                            "stuck activation %s: turn running %.1fs "
                            "(limit %.1fs) — deactivating", act.grain_id,
                            act.oldest_running_age(), stuck_limit)
                        self.silo.stats.increment(
                            "catalog.activations.stuck")
                        self.schedule_deactivation(act, stuck=True)
                    continue
                if now < act.keep_alive_until:
                    continue
                age_limit = getattr(act.grain_class,
                                    "__orleans_collection_age__",
                                    self.silo.config.collection_age)
                if act.idle_for() > age_limit:
                    self.schedule_deactivation(act)

    async def collect_idle(self, max_age: float = 0.0) -> int:
        """Forced collection (ManagementGrain.ForceActivationCollection):
        deactivate idle application activations idle ≥ ``max_age``."""
        n = 0
        for act in list(self.by_activation.values()):
            if act.grain_id.is_system_target():
                continue
            if act.state != ActivationState.VALID or not act.is_inactive:
                continue
            if act.idle_for() >= max_age:
                await self._deactivate(act)
                n += 1
        return n

    # ------------------------------------------------------------------
    def activation_count(self) -> int:
        """Application activations (system targets excluded, matching the
        management-grain activation-count semantics)."""
        return sum(1 for a in self.by_activation.values()
                   if not a.grain_id.is_system_target())

    def on_silo_dead(self, silo_address) -> None:
        """Kill activations whose directory registration lived on a dead silo
        (Catalog.OnSiloStatusChange, Catalog.cs:175,1400) — handled by the
        locator invalidating its partition; local activations stay valid."""
