"""Execution context: which activation is running, plus request baggage.

The reference pins the current scheduling context in TLS
(/root/reference/src/Orleans.Core/Runtime/RuntimeContext.cs) and flows
user baggage via ``RequestContext``
(Core.Abstractions/Runtime/RequestContext.cs). asyncio's ``contextvars``
give both for free — a turn is an awaited coroutine, and context vars
propagate through awaits exactly like the reference's logical call context.
"""

from __future__ import annotations

import contextvars
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from .activation import ActivationData

# The activation whose turn is currently executing (RuntimeContext TLS).
current_activation: contextvars.ContextVar["ActivationData | None"] = (
    contextvars.ContextVar("orleans_current_activation", default=None)
)

# User baggage propagated in message headers (RequestContext).
_request_context: contextvars.ContextVar[dict | None] = contextvars.ContextVar(
    "orleans_request_context", default=None
)

# RequestContext key the ambient TransactionInfo rides under (shared with
# transactions.context; the runtime needs it to piggyback callee joins on
# response headers without importing the transactions package)
TXN_KEY = "orleans.txn"


def build_call_chain(sender: "ActivationData | None") -> tuple:
    """Extend ``sender``'s running call chain with its own grain id for an
    outgoing call (deadlock/reentrancy detection,
    InsideRuntimeClient.cs:306-311); () outside any turn.  The single
    construction shared by the messaging send path, the direct-interleave
    lane, and the hot lane — chain semantics changes happen HERE once."""
    if sender is None:
        return ()
    running = sender.running[-1] if sender.running else None
    parent = running.call_chain if running is not None else ()
    return (*parent, sender.grain_id)


def current_call_chain() -> tuple:
    """:func:`build_call_chain` for the ambient activation."""
    return build_call_chain(current_activation.get())


class RequestContext:
    """Static accessors mirroring the reference API
    (``RequestContext.Get/Set/Remove``)."""

    @staticmethod
    def get(key: str, default: Any = None) -> Any:
        ctx = _request_context.get()
        return default if ctx is None else ctx.get(key, default)

    @staticmethod
    def set(key: str, value: Any) -> None:
        ctx = dict(_request_context.get() or {})
        ctx[key] = value
        _request_context.set(ctx)

    @staticmethod
    def remove(key: str) -> None:
        ctx = dict(_request_context.get() or {})
        ctx.pop(key, None)
        _request_context.set(ctx or None)

    @staticmethod
    def export() -> dict | None:
        """Snapshot for message headers (``RequestContextExtensions.Export``)."""
        ctx = _request_context.get()
        return dict(ctx) if ctx else None

    @staticmethod
    def import_(ctx: dict | None) -> None:
        _request_context.set(dict(ctx) if ctx else None)

    @staticmethod
    def clear() -> None:
        _request_context.set(None)
