"""Batched response egress: the per-destination flush accumulator.

The egress twin of the PR-7 ingress pipeline's hand-off layer. Every
inbound batch that resolves N futures in one completion — a device-tick
``_complete_job``, a ``receive_vector_batch`` error bounce, the eager
host turns of one delivered batch — used to fan out N per-message
``send_response`` → ``transmit`` → ``MessageCenter.send_message`` hops
on the way back. The accumulator groups those responses per origin
(silo address / gateway connection) and hands each group to the fabric
as ONE unit (``MessageCenter.send_batch`` → one ``encode_message_batch``
write per destination).

Flush discipline — latency-neutral by construction:

* ``add`` arms a ``call_soon`` flush on the FIRST response of a burst.
  Future resolutions and eager turn completions of one batch all run
  inside one ready-queue cycle, and the armed flush lands AFTER them in
  the loop's ready deque (it was scheduled during that cycle), so the
  whole burst groups into one flush without any explicit begin/end
  bracketing — and a singleton response flushes alone one callback
  later, before any newly-ready IO callbacks (selector wakeups append
  behind it). Nothing is ever held across a loop turn.
* ``flush_dest`` is the per-destination FIFO guard:
  ``MessageCenter.send_message`` drains a pending group for a
  destination before any per-message send to it, so a response handed
  to the accumulator can never be overtaken by a later message on the
  same link (all the wire ever guaranteed: per-sender FIFO per target).

Scope: APPLICATION responses only. PING/SYSTEM responses (membership
probes, directory/management control RPCs) keep the per-message path —
they are latency-critical and low-volume, and the armed flush runs at
the END of the loop's current ready run, which under saturation can
exceed a probe timeout (observed as a false-death vote spiral in the
chaos soak before the split). This is the same QoS split the
category-partitioned inbound queues exist for.

``SiloConfig.batched_egress=False`` never constructs one of these —
``Dispatcher.send_response`` then takes the per-message path bit for
bit, the A/B lever symmetric with ``batched_ingress``.
"""

from __future__ import annotations

import asyncio
import time

from ..core import message as _msg_mod
from ..observability.stats import COUNT_BOUNDS, EGRESS_STATS

_BUILD = EGRESS_STATS["build"]
_DWELL = EGRESS_STATS["dwell"]
_GROUP = EGRESS_STATS["group"]
_RESPONSES = EGRESS_STATS["responses"]

__all__ = ["EgressBatcher"]


class EgressBatcher:
    """Per-destination response groups with an armed end-of-burst flush
    (see module docstring). One per MessageCenter when
    ``batched_egress`` is on; the dispatcher's ``send_response`` feeds
    it for every remote-bound response."""

    __slots__ = ("center", "groups", "_armed", "stats", "last_group",
                 "_sharded_dest")

    def __init__(self, center):
        self.center = center
        self.groups: dict = {}       # destination SiloAddress -> [Message]
        self._armed = False
        # same gating as the ingest stages: the silo's registry when
        # metrics_enabled, else None — add/flush pay one None check
        self.stats = center.silo.ingest_stats
        self.last_group = 0          # last flush-group size (sampler gauge)
        # sharded egress (SocketFabric.sharded_dest): a destination
        # whose encode runs on an egress shard keeps its dwell stamps
        # through the hand-off — the SHARD observes dwell at encode
        # time (accumulator + ring + sender-queue wait, replayed
        # loop-side), strictly more truthful than flush-time here
        self._sharded_dest = getattr(
            getattr(center.silo, "fabric", None), "sharded_dest", None)

    def add(self, dest, msg) -> None:
        """Join ``msg`` to the pending group for ``dest`` and arm the
        end-of-burst flush."""
        if _msg_mod._DEBUG_POOL:
            # pool poisoning: accumulating a recycled shell would put
            # another call's response on the wire at flush
            _msg_mod.assert_live(msg, "egress.add")
        if self.stats is not None:
            # dwell stamp: the received_at slot is wire-excluded and
            # dead on an outbound response (receivers re-stamp on
            # arrival); cleared again at flush so in-proc deliveries
            # never mistake the send-side stamp for an arrival
            msg.received_at = time.monotonic()
        g = self.groups.get(dest)
        if g is None:
            g = self.groups[dest] = []
        g.append(msg)
        if not self._armed:
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                # no running loop (sync harness/unit contexts): hand off
                # immediately — correctness over grouping
                self.flush()
                return
            self._armed = True
            loop.call_soon(self.flush)

    def _observe_group(self, dest, msgs: list) -> None:
        """Shared per-group bookkeeping for both flush paths: group-size
        histogram, responses counter, and per-message dwell (observed and
        cleared BEFORE the hand-off — encode/transport time belongs to
        the ``encode`` stage, not here). A sharded destination keeps its
        dwell stamps: the egress shard observes them at encode time
        (dwell then spans accumulator + ring + sender queue) and replays
        loop-side."""
        st = self.stats
        n = len(msgs)
        self.last_group = n
        if st is None:
            return
        st.histogram_with(_GROUP, COUNT_BOUNDS).observe(n)
        st.increment(_RESPONSES, n)
        sd = self._sharded_dest
        if sd is not None and sd(dest):
            return  # dwell observed (and cleared) shard-side
        now = time.monotonic()
        for m in msgs:
            if m.received_at is not None:
                st.observe(_DWELL, now - m.received_at)
                m.received_at = None

    def flush(self) -> None:
        """Hand every pending group to the message center, one
        ``send_batch`` per destination (the batch-completion boundary)."""
        self._armed = False
        groups = self.groups
        if not groups:
            return
        self.groups = {}
        st = self.stats
        center = self.center
        if st is None:
            for dest, msgs in groups.items():
                self.last_group = len(msgs)
                center.send_batch(dest, msgs)
            return
        # the build window covers ONLY the grouping/bookkeeping work —
        # the hand-off below runs outside it so the stage decomposition
        # stays non-overlapping (encode times itself in the wire layer,
        # transport write is not an egress stage)
        t0 = time.perf_counter()
        for dest, msgs in groups.items():
            self._observe_group(dest, msgs)
        st.observe(_BUILD, time.perf_counter() - t0)
        for dest, msgs in groups.items():
            center.send_batch(dest, msgs)

    def flush_dest(self, dest) -> None:
        """FIFO guard: drain the pending group for ONE destination now
        (called before a per-message send to it — see module docstring)."""
        msgs = self.groups.pop(dest, None)
        if not msgs:
            return
        self._observe_group(dest, msgs)
        self.center.send_batch(dest, msgs)
