"""ActivationData: per-activation state machine, mailbox, turn gate.

Re-design of /root/reference/src/Orleans.Runtime/Catalog/ActivationData.cs
(mailbox ``EnqueueMessage:566``, running-state ``RecordRunning:475``, overload
``CheckOverloaded:616``, waiting queue :662-697) fused with the reentrancy
gate from ``Dispatcher.ActivationMayAcceptRequest/CanInterleave``
(Dispatcher.cs:313-336).

The asyncio re-design: instead of a WorkItemGroup + ActivationTaskScheduler
pair (two-level scheduler over OS threads, Scheduler/WorkItemGroup.cs:12),
single-threaded-turn semantics fall out of the event loop — a turn is one
awaited request coroutine; the gate below decides whether an incoming request
starts now or waits, which is exactly the serial/interleaved decision the
reference makes, minus the thread machinery.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import time
from enum import IntEnum
from typing import TYPE_CHECKING, Any

from ..core.errors import GrainOverloadedError
from ..core.ids import ActivationAddress, ActivationId, GrainId
from ..core.message import Message

if TYPE_CHECKING:
    from .silo import SiloRuntime


class ActivationState(IntEnum):
    """``ActivationData.State`` machine (ActivationData.cs)."""

    CREATE = 0
    ACTIVATING = 1
    VALID = 2
    DEACTIVATING = 3
    INVALID = 4


DEFAULT_MAX_ENQUEUED = 5000  # LimitManager default analog for overload check


class GrainTimerHandle:
    """Disposable timer registration (GrainTimer.cs:11). Ticks are routed
    through the activation gate so they respect turn semantics.

    ``link`` is the ARMING trace context — the (trace_id, span_id) of the
    turn that registered the timer, when that turn was sampled. Tick
    turns root fresh traces (timer messages carry no headers); arming the
    link in this task's context makes every such root carry a span LINK
    back to the arming trace (observability.tracing.pending_root_link),
    so Perfetto/OTLP show causality without merging the traces."""

    def __init__(self, activation: "ActivationData", callback, due: float,
                 period: float | None, link: tuple | None = None):
        self._activation = activation
        self._callback = callback
        self._period = period
        self._link = link
        self._cancelled = False
        self._task = asyncio.get_running_loop().create_task(self._run(due))

    async def _run(self, due: float) -> None:
        try:
            if self._link is not None:
                # The task context COPIED the arming turn's ambient trace
                # at create_task time; left in place, every tick's calls
                # would join (and keep re-opening) a trace whose root
                # closed long ago — exactly the stale-span pollution tail
                # retention cannot decide. Clear it so tick work roots
                # FRESH traces, and arm the link so each new root carries
                # the arming context as a span link instead.
                from ..observability.tracing import (arm_root_link,
                                                     current_trace)
                current_trace.set(None)
                arm_root_link(self._link)
            await asyncio.sleep(due)
            while not self._cancelled:
                if self._activation.state not in (
                        ActivationState.VALID, ActivationState.ACTIVATING):
                    return
                try:
                    await self._activation.run_timer_turn(self._callback)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — a failing tick must not
                    # kill the periodic timer (GrainTimer logs and continues)
                    logging.getLogger("orleans.timers").exception(
                        "timer tick failed on %s", self._activation.grain_id)
                if self._period is None:
                    return
                await asyncio.sleep(self._period)
        except asyncio.CancelledError:
            pass

    def cancel(self) -> None:
        self._cancelled = True
        self._task.cancel()

    # C#-style alias
    dispose = cancel


class ActivationData:
    """One in-memory activation of a grain."""

    def __init__(self, grain_id: GrainId, runtime: "SiloRuntime",
                 grain_class: type, *, max_enqueued: int = DEFAULT_MAX_ENQUEUED):
        self.grain_id = grain_id
        self.activation_id = ActivationId.new()
        self.runtime = runtime
        self.grain_class = grain_class
        self.grain_instance: Any = None
        self.state = ActivationState.CREATE
        self.storage_bridge = None  # set by Catalog for StatefulGrain
        # class flags resolved once (the reentrancy gate reads these per
        # message, and per-call getattr walks were measurable on the hot
        # lane); plain attributes shadowing what used to be properties
        self.is_reentrant: bool = getattr(
            grain_class, "__orleans_reentrant__", False)
        self.is_stateless_worker: bool = getattr(
            grain_class, "__orleans_stateless_worker__", 0) > 0

        # Turn gate state (ActivationData running/waiting)
        self.running: list[Message] = []          # currently-executing requests
        self.running_since: dict[int, float] = {}  # msg.id → turn start
        self.waiting: collections.deque[Message] = collections.deque()
        self.max_enqueued = max_enqueued

        # Idle collection bookkeeping (ActivationCollector tickets)
        self.last_busy = time.monotonic()
        self.keep_alive_until = 0.0
        self._deactivate_on_idle = False

        self.timers: list[GrainTimerHandle] = []
        # Requests buffered while ACTIVATING (the reference's "dummy
        # activation queues messages while real init runs", Catalog.cs:487-502)
        self.activating_backlog: collections.deque[Message] = collections.deque()

    # ------------------------------------------------------------------
    @property
    def address(self) -> ActivationAddress:
        return ActivationAddress(self.runtime.silo_address, self.grain_id,
                                 self.activation_id)

    # -- reentrancy gate (Dispatcher.cs:313-336) ------------------------
    def may_accept_request(self, msg: Message) -> bool:
        if not self.running:
            return True
        return self.can_interleave(msg)

    def can_interleave(self, msg: Message) -> bool:
        """``Dispatcher.CanInterleave:326``: reentrant class, AlwaysInterleave
        method, read-only request among read-only turns, or call-chain
        reentrancy (the incoming call originates from our own pending call
        chain — running it avoids self-deadlock, Dispatcher.cs:346-357)."""
        if self.is_reentrant or msg.is_always_interleave:
            return True
        if msg.is_read_only and all(m.is_read_only for m in self.running):
            return True
        if self.grain_id in msg.call_chain:
            return True
        return False

    def check_overloaded(self) -> None:
        """``ActivationData.CheckOverloaded:616`` → Overloaded rejection."""
        if len(self.waiting) >= self.max_enqueued:
            raise GrainOverloadedError(
                f"{self.grain_id}: {len(self.waiting)} requests enqueued "
                f"(limit {self.max_enqueued})")

    # -- running-state bookkeeping (RecordRunning:475) -------------------
    def record_running(self, msg: Message) -> None:
        self.running.append(msg)
        self.running_since[msg.id] = time.monotonic()
        self.last_busy = time.monotonic()

    def reset_running(self, msg: Message) -> None:
        try:
            self.running.remove(msg)
        except ValueError:
            pass
        self.running_since.pop(msg.id, None)
        self.last_busy = time.monotonic()

    def oldest_running_age(self) -> float:
        """Age of the longest-running turn (stuck-activation probe,
        ActivationData.cs:583-593)."""
        if not self.running_since:
            return 0.0
        return time.monotonic() - min(self.running_since.values())

    @property
    def is_inactive(self) -> bool:
        return not self.running and not self.waiting

    def idle_for(self) -> float:
        return time.monotonic() - self.last_busy

    # -- deactivation hints ---------------------------------------------
    def deactivate_on_idle(self) -> None:
        self._deactivate_on_idle = True

    @property
    def wants_deactivation(self) -> bool:
        return self._deactivate_on_idle and self.is_inactive

    def delay_deactivation(self, seconds: float) -> None:
        self.keep_alive_until = max(self.keep_alive_until,
                                    time.monotonic() + seconds)

    # -- timers ----------------------------------------------------------
    def register_timer(self, callback, due: float,
                       period: float | None) -> GrainTimerHandle:
        from ..observability.tracing import current_trace
        h = GrainTimerHandle(self, callback, due, period,
                             link=current_trace.get())
        self.timers.append(h)
        return h

    def stop_timers(self) -> None:
        for t in self.timers:
            t.cancel()
        self.timers.clear()

    async def run_timer_turn(self, callback) -> None:
        """Run a timer tick as a turn: waits until the gate admits it (timer
        ticks are non-reentrant w.r.t. messages, GrainTimer semantics)."""
        await self.runtime.dispatcher.run_closed_turn(self, callback)

    def __repr__(self) -> str:
        return (f"<Activation {self.grain_id} {self.state.name} "
                f"run={len(self.running)} wait={len(self.waiting)}>")
