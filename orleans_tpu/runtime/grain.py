"""Grain application API (L10).

Re-design of /root/reference/src/Orleans.Core.Abstractions/Core/Grain.cs:15
(OnActivateAsync :220, RegisterTimer :113, RegisterOrUpdateReminder :133,
GetStreamProvider :182, DeactivateOnIdle :196; ``Grain<TState>`` :251,284-297)
and the concurrency attributes
(Concurrency/GrainAttributeConcurrency.cs, Placement/PlacementAttribute.cs).

Python grains need no codegen: a grain class *is* its interface; public async
methods become remote-callable; decorators replace C# attributes.
"""

from __future__ import annotations

import inspect
from typing import TYPE_CHECKING, Any, Callable, TypeVar

from ..core.ids import GrainId, GrainType

if TYPE_CHECKING:
    from .activation import ActivationData
    from .references import GrainRef

T = TypeVar("T")

__all__ = [
    "Grain", "StatefulGrain", "reentrant", "stateless_worker", "read_only",
    "always_interleave", "one_way", "placement", "collection_age",
    "grain_type_of",
]


# ---------------------------------------------------------------------------
# Class / method decorators (C# attribute analogs)
# ---------------------------------------------------------------------------

def reentrant(cls: type) -> type:
    """``[Reentrant]`` — all requests may interleave on this grain's turns."""
    cls.__orleans_reentrant__ = True
    return cls


def stateless_worker(max_local: int = 0) -> Callable[[type], type]:
    """``[StatelessWorker(n)]`` (StatelessWorkerPlacement.cs:6,12-16) —
    auto-scaled local replicas, no directory entry. ``max_local=0`` means
    min(cpu-default) like the reference's processor-count default."""
    def deco(cls: type) -> type:
        cls.__orleans_stateless_worker__ = max(
            max_local, 0) or _DEFAULT_STATELESS_LIMIT
        cls.__orleans_placement__ = "stateless_worker"
        return cls
    return deco


_DEFAULT_STATELESS_LIMIT = 8


def placement(strategy: str) -> Callable[[type], type]:
    """Placement attribute: 'random' | 'prefer_local' | 'hash' |
    'activation_count' (PlacementAttribute.cs)."""
    def deco(cls: type) -> type:
        cls.__orleans_placement__ = strategy
        return cls
    return deco


def collection_age(seconds: float) -> Callable[[type], type]:
    """``[CollectionAgeLimit]`` — per-class idle-deactivation age override
    (GrainCollectionOptions.ClassSpecificCollectionAge; consumed by the
    catalog's idle collector)."""
    def deco(cls: type) -> type:
        cls.__orleans_collection_age__ = float(seconds)
        return cls
    return deco


def read_only(fn: T) -> T:
    """``[ReadOnly]`` — may interleave with other read-only turns."""
    fn.__orleans_read_only__ = True
    return fn


def always_interleave(fn: T) -> T:
    """``[AlwaysInterleave]`` — may interleave with anything."""
    fn.__orleans_always_interleave__ = True
    return fn


def one_way(fn: T) -> T:
    """``[OneWay]`` — fire-and-forget, no response message."""
    fn.__orleans_one_way__ = True
    return fn


def grain_type_of(cls: type) -> GrainType:
    """Stable GrainType for a grain class (the codegen type-code analog)."""
    return GrainType.of(cls.__name__)


# ---------------------------------------------------------------------------
# Grain base class
# ---------------------------------------------------------------------------

class Grain:
    """Base class for host-tier grains (arbitrary Python logic).

    Lifecycle hooks and runtime services mirror ``Grain`` (Grain.cs:15). The
    runtime injects ``_activation`` before ``on_activate`` runs; user code
    accesses services through the properties below, never the runtime
    directly.
    """

    _activation: "ActivationData | None" = None

    # -- identity ----------------------------------------------------------
    @property
    def grain_id(self) -> GrainId:
        return self._activation.grain_id

    @property
    def primary_key(self) -> Any:
        return self._activation.grain_id.key

    @property
    def primary_key_ext(self) -> str | None:
        return self._activation.grain_id.key_ext

    # -- lifecycle hooks (Grain.cs:220,235) --------------------------------
    async def on_activate(self) -> None:  # noqa: B027
        """Called after construction, before the first message turn."""

    async def on_deactivate(self) -> None:  # noqa: B027
        """Called before the activation is destroyed."""

    # -- runtime services --------------------------------------------------
    @property
    def runtime(self):
        """The hosting silo facade (``IGrainRuntime`` — Grain.cs's Runtime):
        grants grains access to silo services, e.g. ``self.runtime.vector``
        for the device tier."""
        return self._activation.runtime

    def get_grain(self, grain_class: type, key: Any,
                  key_ext: str | None = None) -> "GrainRef":
        """``GrainFactory.GetGrain`` from inside a grain (Grain.cs:86-111)."""
        return self._activation.runtime.grain_factory.get_grain(
            grain_class, key, key_ext)

    def register_timer(self, callback, due: float, period: float | None):
        """Volatile per-activation timer; ticks run as turns on this
        activation's context (Grain.cs:113, GrainTimer.cs:11). Returns a
        disposable handle."""
        return self._activation.register_timer(callback, due, period)

    async def register_reminder(self, name: str, due: float, period: float):
        """Durable reminder (Grain.cs:133); requires the grain to implement
        ``receive_reminder``."""
        return await self._activation.runtime.reminders.register_or_update(
            self.grain_id, name, due, period)

    async def unregister_reminder(self, name: str) -> None:
        await self._activation.runtime.reminders.unregister(self.grain_id, name)

    async def get_reminder(self, name: str):
        return await self._activation.runtime.reminders.get(self.grain_id, name)

    def get_stream_provider(self, name: str):
        """``Grain.GetStreamProvider`` (Grain.cs:182)."""
        return self._activation.runtime.get_stream_provider(name)

    def deactivate_on_idle(self) -> None:
        """``DeactivateOnIdle`` (Grain.cs:196): mark for deactivation as soon
        as the current turn (and queued work) completes."""
        self._activation.deactivate_on_idle()

    def delay_deactivation(self, seconds: float) -> None:
        self._activation.delay_deactivation(seconds)

    @property
    def runtime_identity(self) -> str:
        return str(self._activation.runtime.silo_address)


class StatefulGrain(Grain):
    """``Grain<TState>`` (Grain.cs:251): declarative persisted state.

    ``state`` is any picklable object (dict by default); storage round-trips
    through the silo's configured ``IGrainStorage`` provider with etag checks
    (StateStorageBridge.cs:11,49,80,107).
    """

    STORAGE_PROVIDER: str | None = None  # None → silo default provider

    def __init__(self) -> None:
        self.state: Any = {}

    @property
    def _bridge(self):
        return self._activation.storage_bridge

    async def read_state(self) -> None:
        """``ReadStateAsync`` (Grain.cs:284)."""
        data = await self._bridge.read()
        if data is not None:
            self.state = data

    async def write_state(self) -> None:
        """``WriteStateAsync`` (Grain.cs:290)."""
        await self._bridge.write(self.state)

    async def clear_state(self) -> None:
        """``ClearStateAsync`` (Grain.cs:297)."""
        await self._bridge.clear()
        self.state = {}


def remote_methods(cls: type) -> dict[str, Callable]:
    """Public async methods of a grain class = its remote interface
    (the codegen GrainInterfaceMap analog). Device-tier grain classes
    (dispatch.VectorGrain) expose their @actor_method handlers instead —
    the same GrainRef proxies both tiers.

    Cached per class: a GrainRef is built on every get_grain call, and
    inspect.getmembers per ref was ~20% of host-tier call time."""
    cached = cls.__dict__.get("__orleans_remote_methods__")
    if cached is not None:
        return cached
    from ..dispatch.vector_grain import ActorMethod, VectorGrain

    if isinstance(cls, type) and issubclass(cls, VectorGrain):
        out = {name: m.fn for name in dir(cls)
               if isinstance((m := getattr(cls, name)), ActorMethod)}
    else:
        out = {}
        for name, fn in inspect.getmembers(cls, inspect.isfunction):
            if name.startswith("_"):
                continue
            if name in _GRAIN_BASE_METHODS:
                continue
            if inspect.iscoroutinefunction(fn):
                out[name] = fn
    cls.__orleans_remote_methods__ = out
    return out


_GRAIN_BASE_METHODS = frozenset(
    n for n, f in inspect.getmembers(Grain, inspect.isfunction)
) | frozenset(
    n for n, f in inspect.getmembers(StatefulGrain, inspect.isfunction)
)
