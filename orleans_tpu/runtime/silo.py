"""Silo: composition root, lifecycle, message center, hosting builder.

Re-design of /root/reference/src/Orleans.Runtime/Silo/Silo.cs:39 (ctor wiring
:124-260, StartAsync:267, staged start :377-564, stop :663-802), the hosting
builder (Hosting/Generic/SiloHostBuilder.cs:13, DefaultSiloServices.cs:99-195),
and the silo transport (Runtime/Messaging/MessageCenter.cs:12,
IncomingMessageAgent.cs:43, InboundMessageQueue.cs — three QoS queues with
dedicated draining).

The in-proc fabric (orleans_tpu.runtime.cluster.InProcFabric) replaces
sockets for single-host clusters and tests; the TPU data plane for vectorized
grains rides device collectives (orleans_tpu.parallel.transport) instead of
either.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Callable

from ..core.ids import GrainId, SiloAddress
from ..core.message import Category, Direction, Message
from ..core.serialization import copy_call_body, copy_result
from ..observability.stats import DISPATCH_STATS, StatsRegistry
from ..observability.stats import INGEST_STATS as _INGEST
from ..observability.tracing import mark_remote_if_traced

_INGEST_ENQUEUE = _INGEST["enqueue"]
from .activation import ActivationState
from ..storage.core import StorageManager
from .cancellation import TokenInterner
from .catalog import Catalog
from .context import current_activation, current_call_chain
from .dispatcher import Dispatcher
from .hotlane import marker_ids as _marker_ids
from .hotlane import try_hot_invoke as _hot_invoke
from .invoker import InvokerTable
from .references import GrainFactory
from .runtime_client import RuntimeClient

if TYPE_CHECKING:
    from .cluster import InProcFabric

log = logging.getLogger("orleans.silo")

__all__ = ["SiloConfig", "Silo", "SiloBuilder", "ServiceLifecycleStage"]

# eager_task_factory is a per-loop setting shared by every silo on the
# loop (and the embedding app). Refcount installs so the last silo to
# stop restores whatever factory the application had before.
_eager_refs: dict[int, tuple[int, Any]] = {}


def _install_eager_factory(loop: asyncio.AbstractEventLoop) -> None:
    if not hasattr(asyncio, "eager_task_factory"):
        return  # pre-3.12 runtime: turns run through the ordinary factory
    key = id(loop)
    if key in _eager_refs:
        n, prev = _eager_refs[key]
        _eager_refs[key] = (n + 1, prev)
        return
    _eager_refs[key] = (1, loop.get_task_factory())
    loop.set_task_factory(asyncio.eager_task_factory)


def _uninstall_eager_factory(loop: asyncio.AbstractEventLoop) -> None:
    key = id(loop)
    if key not in _eager_refs:
        return
    n, prev = _eager_refs[key]
    if n <= 1:
        del _eager_refs[key]
        loop.set_task_factory(prev)
    else:
        _eager_refs[key] = (n - 1, prev)


class ServiceLifecycleStage:
    """Ordered stages (Core/Lifecycle/ServiceLifecycleStage.cs)."""

    RUNTIME_INITIALIZE = 2000
    RUNTIME_SERVICES = 4000
    RUNTIME_GRAIN_SERVICES = 6000
    APPLICATION_SERVICES = 8000
    ACTIVE = 10000


@dataclass
class SiloConfig:
    """Typed options (the Options-classes analog: SchedulingOptions,
    GrainCollectionOptions, SiloMessagingOptions defaults)."""

    name: str = "silo"
    cluster_id: str = "default"
    service_id: str = "default"
    response_timeout: float = 30.0
    # a turn older than this is "stuck": the activation is abandoned and
    # rebuilt (SiloMessagingOptions.MaxRequestProcessingTime)
    max_request_processing_time: float = 60.0
    # gateway load shedding (LoadSheddingOptions): when enabled, client
    # ingress is rejected GATEWAY_TOO_BUSY once the application inbound
    # queue backs up past the limit (the queue-depth analog of the
    # reference's CPU-threshold shed)
    load_shedding_enabled: bool = False
    load_shedding_limit: int = 10_000
    # queue-wait-trend shedding (the INGEST_STATS backpressure signal):
    # when > 0, client ingress is also shed while the WINDOWED mean of
    # observed ingest queue-wait (host turn start + device batch start)
    # exceeds this many seconds — depth alone misses slow-drain overload
    # where the queue stays short but every message waits long
    load_shedding_queue_wait: float = 0.0
    load_shedding_window: float = 5.0
    # batched ingress (the batched-ingress pipeline, wire.decode_frames →
    # MessageCenter.deliver_batch → grouped vector enqueue): off = the
    # per-frame decode + per-message hand-off (the A/B lever; bytes on
    # the wire are identical either way)
    batched_ingress: bool = True
    # multi-loop silo ingress (runtime.multiloop): N >= 2 spawns N
    # dedicated ingress pump threads, each running its own event loop
    # with its own (vectored, hotwire.sock_recv_batch) socket pump; the
    # listener hands accepted connections round-robin and decoded
    # batches ride SPSC hand-off rings to this loop's turn machinery.
    # PING/SYSTEM traffic bypasses the rings (QoS). Default 1 = today's
    # single-loop in-loop pump bit for bit; in-proc fabrics have no
    # sockets and ignore the knob.
    ingress_loops: int = 1
    # sharded egress (runtime.multiloop.EgressShardPool, ISSUE 15): the
    # outbound twin of ingress_loops. N >= 1 moves silo-peer senders
    # (dial + encode + write) and shard-owned client-route response
    # encode+writev onto shard loops, fed over SPSC egress rings from
    # this loop — borrowing the ingress shard that owns the inbound
    # half of the same peering when ingress_loops >= 2 (link-ownership
    # affinity), else spawning N dedicated egress loop threads.
    # PING/SYSTEM traffic bypasses the rings per-message (QoS).
    # Default 0 = today's main-loop senders/encode bit for bit (the
    # A/B lever); in-proc fabrics have no sockets and ignore the knob.
    egress_shards: int = 0
    # multi-process silo (runtime.multiproc, ISSUE 18): N >= 2 forks N
    # single-GIL worker processes at start(). Each worker is a full
    # cluster-member silo that binds the SAME advertised endpoint with
    # an SO_REUSEPORT listener — the kernel balances accepted
    # connections across workers and a connection pins to its accepting
    # worker for life (senders hash grains to connections, so the
    # multiloop per-grain FIFO argument carries over verbatim; host
    # activations live in the accepting worker). The device engine is
    # owned by THIS process only: workers feed vector calls through
    # cross-process SPSC staging rings on multiprocessing.shared_memory
    # and completions ride per-worker response rings back. Default 1 =
    # today's single-process path bit for bit (the A/B lever). Requires
    # a SocketFabric and a file-backed membership table.
    worker_procs: int = 1
    # batched egress (the response-path twin of batched_ingress):
    # responses resolved from one inbound batch group per origin in a
    # per-destination flush accumulator (runtime.egress.EgressBatcher)
    # and ride ONE MessageCenter.send_batch → encode_message_batch write
    # per destination (header-prefix template on the native build),
    # instead of N per-message send_response → transmit hops. Off = the
    # per-message response path bit for bit (the A/B lever; wire bytes
    # are identical either way)
    batched_egress: bool = True
    # off-loop device-tick pipeline (dispatch.engine): the staging fill,
    # operand upload, kernel dispatch, and host materialize sync of every
    # vector tick run on a dedicated worker thread behind a tick-
    # serialization fence, so host turns and the socket pump interleave
    # with device hand-off instead of queueing behind it. Off = today's
    # loop-inline tick (the A/B lever; results and turn semantics are
    # identical either way)
    offloop_tick: bool = True
    collection_age: float = 2 * 3600.0
    collection_quantum: float = 60.0
    max_enqueued_requests: int = 5000
    deactivation_timeout: float = 5.0
    detect_deadlocks: bool = False
    membership_probe_period: float = 1.0
    membership_probe_timeout: float = 1.0
    membership_missed_probes_limit: int = 3
    membership_votes_needed: int = 2
    membership_num_probed: int = 3
    membership_iam_alive_period: float = 5.0
    membership_refresh_period: float = 5.0
    membership_vote_expiration: float = 10.0
    directory_cache_size: int = 100_000
    # adaptive directory cache (AdaptiveGrainDirectoryCache.cs:178):
    # per-entry TTL doubles on revalidation up to the max; the maintainer
    # refreshes hot entries every refresh period (0 disables the loop)
    directory_cache_initial_ttl: float = 5.0
    directory_cache_max_ttl: float = 120.0
    directory_cache_refresh_period: float = 2.0
    turn_warning_length: float = 0.2  # TurnWarningLengthThreshold
    # distributed request tracing (observability.tracing /
    # config.TracingOptions): when enabled, a SpanCollector on the silo
    # records client/server/network/directory/device/migration spans for
    # requests head-sampled at trace_sample_rate, into a ring buffer of
    # trace_buffer_size spans (management surface + Perfetto export read
    # it). Disabled: zero collector, one None-check per hot-path site.
    trace_enabled: bool = False
    trace_sample_rate: float = 1.0
    trace_buffer_size: int = 4096
    # tail-based retention (config.TracingOptions.tail_*): keep/drop moves
    # from the head roll to trace completion — slow/errored/forced traces
    # survive, the rest drop after the quiescence window. Legs of traces
    # rooted on other silos buffer up to trace_tail_leg_ttl awaiting the
    # rooting silo's retention pull (ctl_trace_spans), then expire.
    trace_tail_enabled: bool = False
    trace_tail_window: float = 0.25
    trace_tail_slow_threshold: float = 0.1
    trace_tail_slow_percentile: float = 0.0
    # auto-tune the tail slow threshold from the root-duration percentile
    # history (LatencyErrorPolicy auto mode; config.TracingOptions.tail_auto)
    trace_tail_auto: bool = False
    trace_tail_leg_ttl: float = 2.0
    trace_tail_max_pending: int = 256
    # streaming OTLP/HTTP export of retained spans (export.OtlpSink);
    # None = no sink. Unreachable collectors degrade to counted drops.
    trace_otlp_endpoint: str | None = None
    trace_otlp_batch_size: int = 64
    trace_otlp_flush_interval: float = 0.5
    # ship OTLP bodies as protobuf wire bytes instead of the JSON mapping
    # (opt-in; requires google.protobuf importable, else falls back to
    # JSON with a warning — the JSON path is untouched when off)
    trace_otlp_protobuf: bool = False
    # live rebalancer (orleans_tpu.rebalance): plan/execute period in
    # seconds (0 disables the loop even when the service is installed),
    # per-round migration budget, and the hot/mean load ratio below which
    # a round is a no-op (hysteresis: don't churn a balanced cluster)
    rebalance_period: float = 0.0
    rebalance_budget: int = 8
    rebalance_imbalance_ratio: float = 1.2
    # ledger-fed host-tier rebalancing (ISSUE 17): when enabled (and the
    # ledger is on), the planner also plans moves for grains whose
    # CHARGED seconds run hot against the per-key mean — load the
    # activation-count signal cannot see
    rebalance_use_ledger: bool = False
    # run new turn tasks eagerly to their first suspension
    # (asyncio.eager_task_factory): a turn that completes without awaiting
    # skips the event-loop round trip entirely — the asyncio analog of the
    # reference's inline WorkItemGroup execution (WorkItemGroup.cs:269
    # runs queued tasks synchronously on the worker thread)
    eager_turns: bool = True
    # hot-lane dispatch (runtime.hotlane): frame-collapsed inline turns for
    # local gate-admitting calls. Off → every call takes the full messaging
    # path (the perf-floor A/B lever; semantics are identical either way)
    hot_lane_enabled: bool = True
    # live metrics pipeline (observability.metrics / config.MetricsOptions):
    # stage-level ingest instrumentation (decode/enqueue/queue-wait/
    # staging/transfer/tick histograms against the envelope's received_at
    # stamp) + the queue/backpressure sampler loop. Off = one attribute
    # check per instrumented site (guarded by
    # tests/test_perf_floors.py::test_floor_metrics_overhead when on).
    metrics_enabled: bool = False
    metrics_sample_period: float = 1.0
    metrics_window: float = 60.0
    # Prometheus/OpenMetrics pull endpoint (GET /metrics, stdlib HTTP):
    # None = no server, 0 = ephemeral port (read back from
    # silo.metrics_server.port)
    metrics_port: int | None = None
    # periodic OTLP metrics push (export.OtlpMetricsSink); None = no sink
    metrics_otlp_endpoint: str | None = None
    metrics_otlp_period: float = 5.0
    # protobuf wire encoding for the metrics push (same gate/fallback as
    # trace_otlp_protobuf)
    metrics_otlp_protobuf: bool = False
    # host-loop occupancy profiler + flight recorder (observability.
    # profiling.LoopProfiler / config.ProfilingOptions): when enabled the
    # silo interposes on its event loop's call_soon/call_at and buckets
    # every callback's wall time into named categories (turns, device
    # tick schedule/staging/transfer/SYNC, socket pump, storage IO,
    # observability internals, idle), keeps a bounded ring of per-window
    # occupancy slices + top-K slowest callbacks, and snapshots the ring
    # on anomalies (load shed, watchdog lag, tail-retained traces). Off
    # (default): NOTHING is installed — the loop keeps its class methods
    # and hot paths pay one None check per site.
    # SLO engine (observability.slo / config.SloOptions): a per-silo
    # SloMonitor loop evaluating declarative objectives (app ingest
    # latency, membership probe RTT, turn errors, gateway shed rate —
    # or silo.slo_specs) every slo_period seconds with multi-window
    # burn-rate detection (fast window catches, slow window confirms,
    # both over slo_burn_threshold× the error budget). Breach →
    # flight-recorder snapshot + tail-trace force-retention + slo.*
    # counters/gauges + telemetry event; cluster rollup via
    # ManagementGrain.get_cluster_slo. Evaluation rides interval-diffed
    # registry snapshots — zero new hot-path instrumentation.
    slo_enabled: bool = False
    slo_period: float = 1.0
    slo_fast_window: float = 60.0
    slo_slow_window: float = 300.0
    slo_burn_threshold: float = 4.0
    slo_min_events: int = 10
    slo_latency_threshold: float = 0.1
    slo_latency_target: float = 0.99
    slo_probe_target: float = 0.99
    slo_error_target: float = 0.999
    slo_shed_target: float = 0.99
    # stream delivery latency objective (publish -> consumer-turn, fed
    # from the streams.delivery.seconds histogram; metrics-gated like
    # app_latency — zero observations never burn)
    slo_stream_target: float = 0.99
    slo_stream_threshold: float = 0.25
    # device-tier streams (streams.device / config.StreamOptions):
    # device_fanout arms the bulk-collective delivery lever on the
    # persistent providers' vector path (stream_fanout edge exchanges
    # for dense bulk items); OFF keeps the per-consumer call_batch path
    # bit for bit — the A/B lever. cache_capacity bounds each device
    # namespace's PooledQueueCache (batches; pressure at 75%).
    stream_device_fanout: bool = False
    stream_device_cache_capacity: int = 1024
    # cost-attribution ledger (observability.ledger / config.
    # LedgerOptions): when enabled the silo charges every unit of work —
    # host-turn exec/queue seconds, device row-seconds, wire bytes per
    # route, stream deliveries — to (grain_class, method) × hashed-key ×
    # tenant, bounded by top-K space-saving sketches. Off (default):
    # silo.ledger is None, every charge site pays one attribute check.
    ledger_enabled: bool = False
    ledger_top_k: int = 32
    # label ("Class/key") -> tenant hook; host turns also read the
    # caller's "orleans.tenant" RequestContext baggage
    ledger_tenant_of: object = None
    profiling_enabled: bool = False
    profiling_window: float = 1.0          # seconds per occupancy slice
    profiling_ring: int = 120              # slices retained (flight data)
    profiling_top_k: int = 8               # slowest callbacks per window
    profiling_trigger_interval: float = 1.0  # min seconds between
    # snapshots per trigger reason (a shed storm -> one snapshot/interval)
    profiling_lag_threshold: float = 0.25  # sampler loop-lag over this
    # triggers a flight-recorder snapshot (watchdog triggers separately
    # at its own lag_warning)


class GrainRegistry:
    """interface-name → grain class map + construction
    (GrainTypeManager/GrainTypeManager.cs:19 + DefaultGrainActivator)."""

    def __init__(self) -> None:
        self._classes: dict[str, type] = {}
        self._factories: dict[type, Callable[[], Any]] = {}

    def register(self, *grain_classes: type,
                 factory: Callable[[], Any] | None = None) -> None:
        for cls in grain_classes:
            self._classes[cls.__name__] = cls
            if factory is not None:
                self._factories[cls] = factory

    def resolve(self, interface_name: str) -> type | None:
        return self._classes.get(interface_name)

    def construct(self, cls: type) -> Any:
        f = self._factories.get(cls)
        return f() if f else cls()

    def all_classes(self) -> list[type]:
        return list(self._classes.values())


class MessageCenter:
    """Silo transport endpoint: three category-partitioned inbound queues with
    dedicated pump tasks (InboundMessageQueue + IncomingMessageAgent), and the
    outbound hand-off to the fabric (OutboundMessageQueue)."""

    def __init__(self, silo: "Silo"):
        self.silo = silo
        self.inbound: dict[Category, asyncio.Queue[Message]] = {}
        self._pumps: list[asyncio.Task] = []
        self.running = False
        # ingest stage metrics (INGEST_STATS): cached so _route pays one
        # attribute load when metrics are off
        self._istats = silo.ingest_stats
        # batched response egress (runtime.egress.EgressBatcher): set by
        # the Silo ctor when batched_egress is on, else None — the
        # per-message send path pays one attribute check
        self.egress = None

    def start(self) -> None:
        self.running = True
        loop = asyncio.get_running_loop()
        for cat in Category:
            self.inbound[cat] = asyncio.Queue()
            self._pumps.append(loop.create_task(self._pump(cat)))

    def stop(self) -> None:
        if self.egress is not None:
            # hand any accumulated response groups to the fabric before
            # the center stops accepting work (the armed flush callback
            # may never run once the loop moves on to teardown)
            self.egress.flush()
        self.running = False
        for t in self._pumps:
            t.cancel()
        self._pumps.clear()

    def deliver(self, msg: Message) -> None:
        """Called by the fabric when a message arrives for this silo."""
        if not self.running:
            return
        if msg.received_at is None and (self.silo.tracer is not None
                                        or self.silo.ingest_stats is not None
                                        or self.silo.shed_trend is not None):
            # arrival stamp: queue-wait attribution measures from HERE
            # (inbound queue + mailbox) to turn start — tracing, the
            # ingest stage metrics, and the shed trend share the one
            # envelope slot (socket arrivals were already stamped at
            # decode)
            msg.received_at = time.monotonic()
        cfg = self.silo.config
        if (cfg.load_shedding_enabled
                and msg.category == Category.APPLICATION
                and msg.direction == Direction.REQUEST
                and (msg.target_silo is None
                     or msg.target_silo != self.silo.silo_address)
                and (self.inbound[Category.APPLICATION].qsize()
                     >= cfg.load_shedding_limit
                     or self._queue_wait_trending_high())):
            # gateway ingress under overload: shed before queueing
            # (Gateway load shedding, LoadSheddingOptions; rejection type
            # Message.cs:87-93 GatewayTooBusy). Silo-to-silo traffic is
            # never shed — only client ingress. The shed signal is queue
            # depth OR the windowed ingest queue-wait trend (when
            # configured): depth misses slow-drain overload where the
            # queue stays short but every message waits long.
            self.silo.stats.increment("messaging.gateway.shed")
            lp = self.silo.loop_prof
            if lp is not None:
                # anomaly hook: a shed is exactly the moment the loop's
                # recent occupancy explains — snapshot the flight ring
                # (rate-limited per reason inside trigger)
                depth = self.inbound[Category.APPLICATION].qsize()
                lp.trigger("queue_wait_trend"
                           if depth < cfg.load_shedding_limit
                           else "load_shed", queue_depth=depth)
            if msg.sending_silo is not None:
                from ..core.message import RejectionType, make_rejection
                rej = make_rejection(msg, RejectionType.GATEWAY_TOO_BUSY,
                                     "gateway overloaded; retry")
                rej.target_silo = msg.sending_silo
                self.silo.fabric.deliver(rej)
            return
        q = self.inbound[msg.category]
        if not q.qsize() and not cfg.load_shedding_enabled:
            # (with shedding on, ingress must accumulate in the queue —
            # queue depth IS the shed signal)
            # hot-path shortcut: nothing queued ahead of this message, so
            # routing inline preserves FIFO while skipping a queue hop +
            # pump-task wakeup per message (the asyncio analog of the
            # reference's inline WorkItemGroup execution; silo-to-self
            # sends already short-circuit the same way in
            # Dispatcher.transmit). Backlogged categories keep the queue
            # so shedding and fairness still apply.
            try:
                self._route(msg)
            except Exception:  # noqa: BLE001 — same contract as the pump
                log.exception("inbound routing failed for %s",
                              msg.method_name)
            return
        q.put_nowait(msg)

    def _queue_wait_trending_high(self) -> bool:
        trend = self.silo.shed_trend
        return (trend is not None and
                trend.mean() > self.silo.config.load_shedding_queue_wait)

    def deliver_batch(self, msgs: list) -> None:
        """Batched fabric arrival: the decoded contents of one socket
        read in ONE hand-off. Routing the batch as a unit is the
        queue-wait killer — vector-tier requests coalesce into grouped
        engine enqueues (dispatcher.receive_vector_batch → one
        ``call_group`` per method) instead of N per-message hops, and
        host-tier messages keep their inline-route fast path. Falls back
        to per-message :meth:`deliver` when shedding is enabled (queue
        depth is the shed signal, so ingress must accumulate) or a
        category is backlogged (queue semantics carry fairness then)."""
        if not self.running:
            return
        if (self.silo.tracer is not None or self._istats is not None
                or self.silo.shed_trend is not None):
            now = time.monotonic()
            for m in msgs:
                if m.received_at is None:  # socket arrivals pre-stamped
                    m.received_at = now
        if not self.silo.config.batched_ingress or \
                self.silo.config.load_shedding_enabled or \
                any(q.qsize() for q in self.inbound.values()):
            # per-message fall-back: the RECEIVING silo's A/B lever is
            # honored even when a co-hosted batched-mode silo's fabric
            # pump accepted the connection and grouped the read
            for m in msgs:
                self.deliver(m)
            return
        self._route_batch(msgs)

    def _route_batch(self, msgs: list) -> None:
        """Route one ingress batch inline (FIFO-preserving: nothing is
        queued ahead — deliver_batch checked). Vector-tier requests are
        peeled into per-class groups and handed to the dispatcher as
        units; everything else takes the ordinary per-message route."""
        ist = self._istats
        silo = self.silo
        vgroups: dict[type, list] = {}
        now = time.monotonic() if ist is not None else 0.0
        my_addr = silo.silo_address
        vifaces = silo.vector_interfaces
        cat_counts: dict = {}
        # silo-to-silo responses arriving in one wire batch correlate in
        # one pass (receive_response_batch: one freelist-release sweep)
        # when the batched response path is on; per-message otherwise
        responses: list | None = [] if silo.config.batched_egress else None
        for m in msgs:
            if ist is not None and m.received_at is not None:
                # ingest enqueue stage (~0 inline) — one clock read for
                # the whole batch; re-stamped BEFORE routing, the last
                # safe touch (routing may consume the envelope)
                ist.observe(_INGEST_ENQUEUE, now - m.received_at)
                m.received_at = now
            cat_counts[m.category] = cat_counts.get(m.category, 0) + 1
            if responses is not None and m.direction == Direction.RESPONSE:
                # grouped correlation: futures resolve via call_soon
                # either way, so deferring these past the batch's
                # requests reorders nothing observable
                responses.append(m)
                continue
            if m.direction != Direction.RESPONSE and vifaces:
                vcls = vifaces.get(m.interface_name)
                if vcls is not None:
                    # device-tier call: group — ownership/recovery checks
                    # run in receive_vector_batch (the ring-owner check
                    # there IS the addressing authority for vector keys,
                    # so skipping send_message addressing changes nothing)
                    g = vgroups.get(vcls)
                    if g is None:
                        g = vgroups[vcls] = []
                    g.append(m)
                    continue
            try:
                if m.direction != Direction.RESPONSE and (
                        m.target_silo is None or m.target_silo != my_addr):
                    m.target_silo = None
                    silo.dispatcher.send_message(m)
                else:
                    silo.dispatcher.receive_message(m)
            except Exception:  # noqa: BLE001 — same contract as the pump
                log.exception("inbound routing failed for %s",
                              m.method_name)
        stats = silo.stats
        for cat, c in cat_counts.items():
            # one counter add per category per batch, not per message
            stats.increment(self._RECEIVED_STAT[cat], c)
        if responses:
            try:
                silo.runtime_client.receive_response_batch(responses)
            except Exception:  # noqa: BLE001 — same contract as the pump
                log.exception("batched response correlation failed")
        for vcls, group in vgroups.items():
            try:
                silo.dispatcher.receive_vector_batch(vcls, group)
            except Exception:  # noqa: BLE001
                log.exception("vector batch routing failed for %s",
                              vcls.__name__)

    async def _pump(self, cat: Category) -> None:
        q = self.inbound[cat]
        while True:
            msg = await q.get()
            while True:
                try:
                    self._route(msg)
                except Exception:  # noqa: BLE001
                    log.exception("inbound routing failed for %s",
                                  msg.method_name)
                # drain whatever else arrived in one wakeup (the
                # IncomingMessageAgent drains its queue per scheduling
                # round, not one message per thread turn)
                try:
                    msg = q.get_nowait()
                except asyncio.QueueEmpty:
                    break

    _RECEIVED_STAT = {c: f"messaging.received.{c.name.lower()}"
                      for c in Category}

    def _route(self, msg: Message) -> None:
        ist = self._istats
        if ist is not None and msg.received_at is not None:
            # ingest enqueue stage: decode/arrival -> leaving the inbound
            # queue (inline routing makes this ~0; a backlogged category
            # shows its queue dwell here). Observed and re-stamped BEFORE
            # routing — the dispatcher may consume (and even recycle) the
            # envelope synchronously, so this is the last safe touch.
            now = time.monotonic()
            ist.observe(_INGEST_ENQUEUE, now - msg.received_at)
            msg.received_at = now
        self.silo.stats.increment(self._RECEIVED_STAT[msg.category])
        if msg.direction != Direction.RESPONSE and (
                msg.target_silo is None
                or msg.target_silo != self.silo.silo_address):
            # Gateway ingress / misrouted: address on this silo's authority
            # (Gateway.cs:17 + Dispatcher.AddressMessage)
            msg.target_silo = None
            self.silo.dispatcher.send_message(msg)
        else:
            self.silo.dispatcher.receive_message(msg)

    def send_message(self, msg: Message) -> None:
        """Outbound to another silo/client via the fabric
        (MessageCenter.SendMessage:177-191)."""
        eg = self.egress
        if eg is not None and eg.groups:
            # per-destination FIFO guard: a response group still pending
            # for this destination must reach the fabric BEFORE this
            # per-message send, or the send overtakes responses that
            # were handed off first (per-sender FIFO per target is the
            # wire's one ordering guarantee)
            eg.flush_dest(msg.target_silo)
        self.silo.stats.increment("messaging.sent")
        # "went remote" hint: any traced leg leaving this process means
        # retention must pull peers before export; traces that never pass
        # here are provably silo-local and skip the pull fan-out
        # (silo-local traffic loops back in dispatcher.transmit and never
        # reaches this method)
        mark_remote_if_traced(self.silo.tracer, msg)
        if msg.target_silo is not None and \
                self.silo.fabric.is_dead(msg.target_silo):
            # dead target (MessageCenter SiloDeadOracle, Silo.cs:347):
            # bounce a transient rejection to the sender so callers —
            # including external clients routed through this gateway —
            # re-address instead of waiting out the response timeout
            if msg.direction == Direction.REQUEST and \
                    msg.sending_silo is not None:
                from ..core.message import RejectionType, make_rejection
                rej = make_rejection(msg, RejectionType.TRANSIENT,
                                     f"target silo {msg.target_silo} dead")
                rej.target_silo = msg.sending_silo
                self.silo.fabric.deliver(rej)
            return
        self.silo.fabric.deliver(msg)

    def send_batch(self, dest, msgs: list) -> None:
        """Batched outbound: one response group for ONE destination rides
        a single fabric hand-off (``deliver_group`` — local silos get one
        ``deliver_batch``, gateway client routes one
        ``encode_message_batch`` write, remote silos one sender-queue
        fill). Per-message ``send_message`` semantics are mirrored: the
        sent counter, the went-remote trace hint, and the dead-target
        check (responses to a dead silo drop exactly like
        ``send_message``'s non-request case — there is no caller left to
        bounce to)."""
        self.silo.stats.increment("messaging.sent", len(msgs))
        tracer = self.silo.tracer
        if tracer is not None:
            for m in msgs:
                mark_remote_if_traced(tracer, m)
        fabric = self.silo.fabric
        if dest is not None and fabric.is_dead(dest):
            return
        deliver_group = getattr(fabric, "deliver_group", None)
        if deliver_group is not None:
            deliver_group(dest, msgs)
        else:
            for m in msgs:
                fabric.deliver(m)


# direct-call marker ids come from hotlane.marker_ids: ONE negative-id
# sequence for every running-marker kind, so concurrent direct-lane and
# hot-lane turns on one activation can never collide in running_since
_DIRECT_YIELD_EVERY = 256


class _DirectCallMarker:
    """Stand-in for a Message in ActivationData.running while a
    direct-interleave call executes: enough surface for the reentrancy
    gate (is_read_only), chain building (call_chain), and the
    stuck-activation probe (id keyed into running_since)."""

    __slots__ = ("id", "call_chain")
    is_read_only = False

    def __init__(self, id: int, call_chain: tuple):
        self.id = id
        self.call_chain = call_chain


class InsideRuntimeClient(RuntimeClient):
    """Silo-interior RPC engine (InsideRuntimeClient.cs:28)."""

    def __init__(self, silo: "Silo"):
        super().__init__(response_timeout=silo.config.response_timeout)
        self.silo = silo
        self._direct_calls_since_yield = 0
        self.hot_lane_enabled = silo.config.hot_lane_enabled

    @property
    def silo_address(self) -> SiloAddress:
        return self.silo.silo_address

    def transmit(self, msg: Message) -> None:
        self.silo.dispatcher.send_message(msg)

    def transmit_batch(self, msgs: list) -> None:
        """Batched in-silo hand-off (RuntimeClient.call_batch):
        vector-interface calls peel into per-class groups and ride ONE
        ``Dispatcher.receive_vector_batch`` → grouped ``call_group``
        enqueue, exactly like batched socket ingress; everything else
        takes the ordinary per-message ``send_message`` route. This
        deliberately does NOT go through MessageCenter.deliver_batch:
        that is the GATEWAY ingress surface — in-silo application calls
        must never be load-shed as client ingress (the per-message
        ``transmit`` → dispatcher path sheds nothing), and must not be
        dropped by a message center that has not started."""
        silo = self.silo
        vifaces = silo.vector_interfaces
        vgroups: dict[type, list] = {}
        for m in msgs:
            vcls = (vifaces.get(m.interface_name)
                    if vifaces and m.direction != Direction.RESPONSE
                    else None)
            if vcls is not None:
                # the ring-owner check inside receive_vector_batch IS
                # the addressing authority for vector keys (same
                # rationale as MessageCenter._route_batch)
                vgroups.setdefault(vcls, []).append(m)
            else:
                try:
                    silo.dispatcher.send_message(m)
                except Exception as e:  # noqa: BLE001 — earlier group
                    # members already dispatched: isolate, never raise
                    self._fail_transmit([m], e)
        for vcls, group in vgroups.items():
            try:
                silo.dispatcher.receive_vector_batch(vcls, group)
            except Exception as e:  # noqa: BLE001 — same isolation
                self._fail_transmit(group, e)

    def try_hot_invoke(self, grain_id, grain_class: type,
                       interface_name: str, method_name: str,
                       args: tuple, kwargs: dict,
                       is_read_only: bool = False):
        """Hot lane for grain-to-grain calls inside this silo (see
        runtime.hotlane for the admission conditions)."""
        if not self.hot_lane_enabled:
            return None
        coro = _hot_invoke(self, self.silo, grain_id, grain_class,
                           interface_name, method_name,
                           args, kwargs, is_read_only)
        if coro is None:
            self.hot_fallbacks += 1
        else:
            self.hot_hits += 1
        return coro

    def try_direct_interleave(self, grain_id, method_name: str,
                              args: tuple, kwargs: dict):
        """Direct-coroutine fast path for ALWAYS-INTERLEAVE methods (and
        the transaction protocol's reentrant-TM internals) on a local
        activation. Sound because the mailbox gate would admit such a
        message unconditionally, so queue semantics carry nothing — only
        the invoke remains, minus per-message machinery. Copy isolation
        is preserved (args/result copied exactly as the messaging path
        does); the per-call timeout is intentionally skipped (the
        turn-length watchdog still observes via the running marker).
        Call filters are NOT skipped: when any filter would run on the
        messaging path — outgoing filters, silo incoming filters, or a
        grain-level ``on_incoming_call`` hook — this path declines and
        the call takes the messaging path, so filtered deployments see
        identical interception regardless of placement (mirrors the
        gating in dispatcher._invoke). The call IS visible to activation
        bookkeeping: a running marker keeps deactivation/idle-collection
        from tearing the activation down mid-call, and nested sends from
        inside the callee carry the caller's extended call chain and
        attribute to the callee activation."""
        if self.outgoing_call_filters or self.silo.incoming_call_filters:
            self.hot_fallbacks += 1
            return None
        acts = self.silo.catalog.by_grain.get(grain_id)
        if not acts or len(acts) != 1:
            self.hot_fallbacks += 1
            return None
        act = acts[0]
        if act.state != ActivationState.VALID:
            self.hot_fallbacks += 1
            return None
        if getattr(act.grain_instance, "on_incoming_call", None) is not None:
            self.hot_fallbacks += 1
            return None
        fn = getattr(act.grain_instance, method_name, None)
        if fn is None:
            self.hot_fallbacks += 1
            return None
        self.hot_hits += 1  # the interleave lane is part of DISPATCH_STATS
        return self._direct_interleave_call(act, fn, args, kwargs)

    async def _direct_interleave_call(self, act, fn, args: tuple,
                                      kwargs: dict):
        args, kwargs = copy_call_body(args, kwargs)
        chain = current_call_chain()
        marker = _DirectCallMarker(-next(_marker_ids), chain)
        act.record_running(marker)
        token = current_activation.set(act)
        try:
            # snapshot BEFORE the pump below runs queued turns: a result
            # aliasing grain-internal state must not pick up later writes
            result = copy_result(await fn(*args, **kwargs))
        finally:
            current_activation.reset(token)
            act.reset_running(marker)
            # regular messages that arrived during the call queued behind
            # the running marker; nothing else pumps them for a direct call
            self.silo.dispatcher.run_message_pump(act)
        # amortized fairness yield: a tight loop of non-suspending direct
        # calls must not starve background tasks (membership probes,
        # reminders) — the messaging path yields once per RPC; here one
        # yield per _DIRECT_YIELD_EVERY calls bounds starvation to a few
        # milliseconds (vs probe periods of 250ms+) while keeping the
        # fast path fast: a per-call sleep(0) measured a 2.4x transaction
        # throughput loss, and even every-32 cost ~20% by widening 2PC
        # critical sections under contention
        self._direct_calls_since_yield += 1
        if self._direct_calls_since_yield >= _DIRECT_YIELD_EVERY:
            self._direct_calls_since_yield = 0
            await asyncio.sleep(0)
        return result


class Silo:
    """One silo: the unit of hosting, addressing, and failure."""

    def __init__(self, config: SiloConfig, fabric: "InProcFabric",
                 registry: GrainRegistry, storage: StorageManager):
        self.config = config
        self.fabric = fabric
        self.registry = registry
        self.storage_manager = storage
        self.silo_address = fabric.allocate_address(config.name)
        # multi-process silo (runtime.multiproc): a SEPARATE advertised
        # gateway endpoint reserved with SO_REUSEPORT at construction
        # time (so it is printable/dialable before start). Forked
        # workers join its accept group with their own listeners; the
        # owner never accepts there and closes its copy once the
        # workers are serving. silo_address stays a normal internal
        # endpoint — all silo-to-silo traffic (membership probes,
        # directory ops, forwards) avoids the reuseport group entirely.
        self.advertised_address: SiloAddress | None = None
        # runtime.multiproc.WorkerSupervisor once start() forks
        self.workers: Any = None
        if config.worker_procs > 1:
            try:
                self.advertised_address = fabric.allocate_address(
                    config.name + "-gw", reuseport=True)
            except TypeError:
                from ..core.errors import ConfigurationError
                raise ConfigurationError(
                    "worker_procs > 1 needs a SocketFabric (SO_REUSEPORT "
                    "accept balancing is a kernel feature; the in-proc "
                    "fabric has no kernel)") from None
        self.stats = StatsRegistry()
        # ingest stage instrumentation (observability.stats.INGEST_STATS):
        # the registry when metrics are enabled, else None — every stage
        # site (socket decode, message-center enqueue, dispatcher
        # queue-wait, engine staging/transfer/tick) guards on that None,
        # so the disabled hot path pays one attribute check
        self.ingest_stats = self.stats if config.metrics_enabled else None
        # per-(grain_class, method) call-site latency/error table
        # (observability.stats.CallSiteStats): fed by the dispatcher's
        # turn epilogue when metrics are on — the drill-down an SLO
        # breach resolves to ("which grain methods are hot/slow"), and
        # the per-class load signal placement policies will consume
        self.call_sites = None
        if config.metrics_enabled:
            from ..observability.stats import CallSiteStats
            self.call_sites = CallSiteStats()
        # cost-attribution ledger (observability.ledger): charges every
        # unit of work to (grain_class, method) × hashed-key × tenant —
        # installed only when enabled, every charge site guards on the
        # None (the disabled path costs one attribute check). The
        # ledger.* gauges registered here are evaluated at snapshot time
        # only, so exposure adds no hot-path cost either.
        self.ledger = None
        if config.ledger_enabled:
            from ..observability.ledger import CostLedger
            self.ledger = CostLedger(config.ledger_top_k,
                                     config.ledger_tenant_of)
            self.ledger.register_gauges(self.stats)
        # SLO monitor (observability.slo.SloMonitor): installed at start
        # when slo_enabled; silo.slo_specs (set pre-start by a builder
        # configurator) overrides the default objective set
        self.slo = None
        self.slo_specs = None
        # queue-wait-trend shedding (observability.stats.QueueWaitTrend):
        # installed only when the knob is armed — fed by the dispatcher's
        # turn-start (and the engine's batch-start) queue-wait sites,
        # read by MessageCenter's shed decision
        self.shed_trend = None
        if config.load_shedding_enabled and config.load_shedding_queue_wait > 0:
            from ..observability.stats import QueueWaitTrend
            self.shed_trend = QueueWaitTrend(config.load_shedding_window)
        # metrics pipeline handles (installed at start when configured)
        self.metrics = None          # observability.metrics.MetricsSampler
        self.metrics_server = None   # observability.metrics.MetricsHttpServer
        self.metrics_sink = None     # observability.export.OtlpMetricsSink
        # host-loop occupancy profiler (observability.profiling.
        # LoopProfiler): installed at start when profiling_enabled — every
        # hot-path site guards on this None, so the off path costs one
        # attribute check
        self.loop_prof = None
        # multi-loop ingress pool (runtime.multiloop.IngressLoopPool):
        # created by SocketFabric.register_silo when ingress_loops >= 2,
        # closed (threads joined, rings drained) in stop()
        self.ingress_pool = None
        self._flight_hook = None     # this silo's telemetry trigger hook
        # distributed tracing (observability.tracing): None unless enabled
        # — every hot-path site guards on that None
        self.tracer = None
        if config.trace_enabled:
            from ..observability.tracing import (LatencyErrorPolicy,
                                                 SpanCollector)
            self.tracer = SpanCollector(
                config.name, config.trace_sample_rate,
                config.trace_buffer_size,
                tail=config.trace_tail_enabled,
                tail_window=config.trace_tail_window,
                policy=LatencyErrorPolicy(config.trace_tail_slow_threshold,
                                          config.trace_tail_slow_percentile,
                                          auto=config.trace_tail_auto),
                leg_ttl=config.trace_tail_leg_ttl,
                max_pending=config.trace_tail_max_pending)
            if config.trace_otlp_endpoint:
                from ..observability.export import OtlpSink
                self.tracer.sinks.append(OtlpSink(
                    config.trace_otlp_endpoint, service_name=config.name,
                    batch_size=config.trace_otlp_batch_size,
                    flush_interval=config.trace_otlp_flush_interval,
                    encoding=("protobuf" if config.trace_otlp_protobuf
                              else "json")))
            if config.trace_tail_enabled:
                # retention propagation: when THIS silo retains a trace it
                # pulls the remote legs over the control path before export
                self.tracer.remote_fetcher = self._pull_trace_legs
        # grain cancellation twins (CancellationSourcesExtension)
        self.cancellation_tokens = TokenInterner(self)

        # ctor wiring order mirrors Silo.cs:124-260
        self.runtime_client = InsideRuntimeClient(self)
        self.runtime_client.tracer = self.tracer
        self.message_center = MessageCenter(self)
        self.dispatcher = Dispatcher(self)
        if config.batched_egress:
            # batched response egress (runtime.egress): responses
            # resolved from one inbound batch group per destination and
            # ride one fabric hand-off — send_response feeds it, the
            # armed flush drains it at batch-completion boundaries
            from .egress import EgressBatcher
            self.message_center.egress = EgressBatcher(self.message_center)
            self.dispatcher._egress = self.message_center.egress
        self.catalog = Catalog(self)
        # per-(grain_class, method) invoker table (runtime.invoker): built
        # once per class, consumed by the dispatcher's invoke engine and
        # the hot lane; revalidates on filter registration / version bump
        self.invokers = InvokerTable(self)
        # hot-lane hit/fallback observability (DISPATCH_STATS): the counters
        # live as plain ints on the runtime client; gauges surface them
        rc = self.runtime_client
        self.stats.register_gauge(DISPATCH_STATS["hot_hits"],
                                  lambda: rc.hot_hits)
        self.stats.register_gauge(DISPATCH_STATS["hot_fallbacks"],
                                  lambda: rc.hot_fallbacks)
        self.grain_factory = GrainFactory(self.runtime_client)
        from ..directory.locator import DistributedLocator
        self.locator: Any = DistributedLocator(self)
        self.membership: Any = None       # installed by cluster join (L6)
        self.gsi: Any = None              # installed by add_multicluster (L12)
        self.reminders: Any = None        # installed by reminder service (L11)
        self.transactions: Any = None     # installed by add_transactions (L11)
        # device tier (installed by dispatch.add_vector_grains): interface
        # name → VectorGrain class; matching requests bypass the catalog and
        # join the vector runtime's tick (Dispatcher._handle_vector_request)
        self.vector: Any = None
        self.vector_interfaces: dict[str, type] = {}
        # incoming grain-call filter chain (InsideRuntimeClient.cs:362);
        # outgoing filters live on self.runtime_client
        self.incoming_call_filters: list = []
        self.stream_providers: dict[str, Any] = {}
        self.status = "Created"
        self._lifecycle: list[tuple[int, Callable, Callable]] = []

    # `runtime` facade seen by activations
    @property
    def runtime(self) -> "Silo":
        return self

    @property
    def gateway_endpoint(self) -> str:
        """What clients dial: the SO_REUSEPORT advertised endpoint when
        this silo runs worker processes, else the silo's own endpoint."""
        if self.advertised_address is not None:
            return self.advertised_address.endpoint
        return self.silo_address.endpoint

    def get_stream_provider(self, name: str):
        try:
            return self.stream_providers[name]
        except KeyError:
            raise KeyError(f"no stream provider named {name!r}") from None

    def subscribe_lifecycle(self, stage: int, start, stop=None) -> None:
        """ISiloLifecycle.Subscribe (Silo.cs:864-869)."""
        self._lifecycle.append((stage, start, stop or (lambda: None)))

    # ------------------------------------------------------------------
    async def start(self) -> None:
        """Staged startup (Silo.StartAsync:267; stages :377-564)."""
        from dataclasses import fields as _fields

        # options dump at boot (Runtime/OptionsLogger/)
        for f in _fields(self.config):
            log.info("SiloConfig.%s = %r", f.name,
                     getattr(self.config, f.name))
        self.status = "Joining"
        if self.config.worker_procs > 1 and self.workers is None:
            # fork FIRST — before the message center, profiler, metrics
            # or any other thread-spawning service: each child must
            # begin from a quiet interpreter (only the forking thread
            # survives a fork), and a child never touches inherited
            # loop/jax state
            from .multiproc import WorkerSupervisor
            self.workers = WorkerSupervisor(self)
            self.workers.fork_workers()
            self.workers.attach(asyncio.get_running_loop())
            self.fabric.gateway_drop_endpoint = \
                self.advertised_address.endpoint
        if self.config.eager_turns:
            _install_eager_factory(asyncio.get_running_loop())
            self._eager_installed = True
        if self.config.profiling_enabled:
            self._install_loop_profiler(asyncio.get_running_loop())
        self.message_center.start()          # RuntimeServices
        self.catalog.start()
        if self.config.metrics_enabled:
            from ..observability.metrics import MetricsSampler
            if self.config.metrics_otlp_endpoint:
                from ..observability.export import OtlpMetricsSink
                self.metrics_sink = OtlpMetricsSink(
                    self.config.metrics_otlp_endpoint,
                    service_name=self.config.name,
                    encoding=("protobuf"
                              if self.config.metrics_otlp_protobuf
                              else "json"))
            self.metrics = MetricsSampler(
                self, period=self.config.metrics_sample_period,
                window=self.config.metrics_window,
                otlp_sink=self.metrics_sink,
                otlp_period=self.config.metrics_otlp_period)
            self.metrics.start()
        if self.config.metrics_port is not None:
            from ..observability.metrics import MetricsHttpServer
            self.metrics_server = await MetricsHttpServer(self).start(
                self.config.metrics_port)
        if self.config.slo_enabled:
            from ..observability.slo import SloMonitor
            if not self.config.metrics_enabled and self.slo_specs is None:
                # the latency/error/shed objectives ride the metrics
                # substrate; default_specs installs ONLY the probe-RTT
                # objective without it (a ratio objective whose bad
                # counters still tick against a gated-off total would
                # fabricate 100%-bad intervals)
                log.warning("slo_enabled without metrics_enabled: only "
                            "the probe-RTT objective is installed on %s",
                            self.config.name)
            self.slo = SloMonitor(self, specs=self.slo_specs)
            self.slo.start()
        # replicated journaled grains need the notification target up
        # before any replica confirms events (eventsourcing notifications)
        for cls in self.registry.all_classes():
            if getattr(cls, "__journal_replicated__", False):
                from ..eventsourcing.journaled import (
                    JournalRelayGrain, install_journal_notifier)
                install_journal_notifier(self)
                # geo replication rides an ordinary grain reachable through
                # cluster gateways (the ProtocolGateway analog) — register
                # it wherever replicated journals are hosted
                self.registry.register(JournalRelayGrain)
                break
        if self.vector is not None:
            # vector-hosting silos must accept forwarded bulk stream items
            # even when no stream provider is configured locally — peers'
            # pulling agents route owner-partitioned sub-batches here
            from ..streams.pubsub import install_vector_stream_target
            install_vector_stream_target(self)
        start_exchange = getattr(
            getattr(self.locator, "versions", None), "start_exchange", None)
        if start_exchange is not None:
            start_exchange()  # cluster type-map refresh (TypeManager)
        start_maint = getattr(self.locator, "start_cache_maintainer", None)
        if start_maint is not None:
            start_maint()  # adaptive directory-cache refresh loop
        self.fabric.register_silo(self)
        for stage, start, _ in sorted(self._lifecycle, key=lambda x: x[0]):
            r = start()
            if asyncio.iscoroutine(r):
                await r
        if self.membership is not None:
            await self.membership.become_active()
        if self.workers is not None:
            # every worker serving its reuseport listener, then retire
            # the owner's never-accepting copy — from here the kernel
            # balances ALL client ingress across the worker processes
            await self.workers.wait_ready()
        self.status = "Running"
        log.info("silo %s running", self.silo_address)

    async def stop(self, graceful: bool = True) -> None:
        """Stop path (Silo.cs:663-802). ``graceful=False`` ≈ kill: no
        deactivations, no membership goodbye — used by liveness tests."""
        if self.status == "Stopped":
            return
        self.status = "ShuttingDown" if graceful else "Dead"
        invalidate = getattr(self.fabric, "invalidate_alive_cache", None)
        if invalidate is not None:
            invalidate()  # stop routing client ingress to this silo now
        if not graceful and self.membership is not None:
            self.membership.stop()  # kill: timers die with us, no goodbye row
        if not graceful:
            self.dispatcher.cancel_turns()
        workers_sup = None
        if self.workers is not None:
            # worker fleet first: each worker silo drains its own
            # clients/turns (final vector calls still resolve through
            # the engine, which is alive until shutdown_worker below),
            # processes join, rings sweep (pushed == drained), segments
            # unlink
            workers_sup = self.workers
            await workers_sup.stop(graceful=graceful)
            self.workers = None
            self.fabric.gateway_drop_endpoint = None
            self.fabric.route_relays.clear()
            if not graceful:
                # kill path: membership timers died above, so no more
                # table writes can land in the auto-provisioned dir
                workers_sup.cleanup_membership_dir()
        if graceful:
            if self.membership is not None:
                await self.membership.shutdown()
            if workers_sup is not None:
                # AFTER the owner's goodbye write: the owner's own
                # iam-alive/refresh timers keep writing the shared table
                # file until the shutdown above
                workers_sup.cleanup_membership_dir()
            # let in-flight turns finish before tearing down the catalog;
            # stragglers past the deactivation budget are cancelled
            await self.dispatcher.drain_turns(self.config.deactivation_timeout)
            await self.catalog.stop()
            # push surviving directory entries (grains hosted on OTHER
            # silos) to ring successors — without this their registrations
            # die with our partition and single-activation breaks
            # (GrainDirectoryHandoffManager on ShuttingDown)
            if hasattr(self.locator, "handoff_all"):
                await self.locator.handoff_all()
            for stage, _, stop in sorted(self._lifecycle, key=lambda x: x[0],
                                         reverse=True):
                r = stop()
                if asyncio.iscoroutine(r):
                    await r
        # background notification/retry tasks must not outlive the runtime
        for t in list(getattr(self, "_journal_notify_tasks", ())):
            t.cancel()
        stop_exchange = getattr(
            getattr(self.locator, "versions", None), "stop_exchange", None)
        if stop_exchange is not None:
            stop_exchange()
        stop_maint = getattr(self.locator, "stop_cache_maintainer", None)
        if stop_maint is not None:
            stop_maint()
        if self.tracer is not None:
            # graceful: decide + export what's buffered; kill: drop it
            await self.tracer.aclose(flush=graceful)
        if self.slo is not None:
            self.slo.stop()
            self.slo = None
        if self.metrics is not None:
            self.metrics.stop()
            if graceful and self.metrics_sink is not None:
                # final snapshot so the collector sees the end state
                self.metrics.push_snapshot()
            self.metrics = None
        if self.metrics_sink is not None:
            await self.metrics_sink.aclose(flush=graceful)
            self.metrics_sink = None
        if self.metrics_server is not None:
            await self.metrics_server.aclose()
            self.metrics_server = None
        egress_pool = getattr(self.fabric, "egress_pool", None)
        if egress_pool is not None and not egress_pool.closed and \
                (egress_pool.owner is self or len(self.fabric.silos) <= 1):
            # sharded-egress shutdown — BEFORE the ingress pool (whose
            # loops the egress shards may be borrowing) and the message
            # center: new sends fall back to the main-loop path, each
            # shard sweeps its ring and flushes its senders on its own
            # loop, standalone threads join (the clean-shutdown drain;
            # pushed == drained afterwards). Runs when the pool's owner
            # silo stops or when we are the last local silo.
            await egress_pool.aclose()
            self.fabric.egress_pool = None
        if self.ingress_pool is not None:
            # multi-loop shutdown: stop accepts + pump threads (joined),
            # then drain every SPSC ring on this loop — BEFORE the
            # message center stops, so every already-decoded message
            # still routes (the clean-shutdown drain)
            await self.ingress_pool.aclose()
            self.ingress_pool = None
        if self.vector is not None:
            # off-loop tick worker: queued batches finish FIFO, then the
            # thread exits (their loop-side completion callbacks run as
            # control returns to the loop below). Before the client
            # close so resolved ticks still reach their callers.
            self.vector.shutdown_worker()
        if self.loop_prof is not None:
            from ..observability.profiling import (loop_profiler,
                                                   uninstall_loop_profiler)
            if self._flight_hook is not None:
                try:
                    self.loop_prof.trigger_hooks.remove(self._flight_hook)
                except ValueError:
                    pass
                self._flight_hook = None
            uninstall_loop_profiler(asyncio.get_running_loop())
            self.loop_prof = None
            self.dispatcher._loop_prof = None
            self.storage_manager.loop_prof = None
            if hasattr(self.fabric, "loop_prof"):
                # co-hosted silos share ONE refcounted profiler per
                # loop: hand the fabric whatever is still installed
                # (None after the LAST uninstall) instead of clearing a
                # hook a surviving silo's egress attribution still needs
                self.fabric.loop_prof = loop_profiler(
                    asyncio.get_running_loop())
            if self.vector is not None:
                self.vector.loop_prof = None
        self.message_center.stop()
        self.runtime_client.close()
        self.fabric.unregister_silo(self, dead=not graceful)
        if getattr(self, "_eager_installed", False):
            self._eager_installed = False
            _uninstall_eager_factory(asyncio.get_running_loop())
        self.status = "Stopped"

    async def _pull_trace_legs(self, trace_id: int) -> list[dict]:
        """Retention propagation (tail tracing): fan ``ctl_trace_spans``
        out to every other alive silo so a trace retained HERE exports
        with its remote legs. SYSTEM-category RPCs never root traces, so
        the pull cannot recursively trace itself; unreachable peers just
        contribute nothing (export stays best-effort)."""
        from ..core.ids import type_code_of
        from ..management.control import SILO_CONTROL, SiloControl
        peers = [a for a in self.locator.alive_list
                 if a != self.silo_address]
        if not peers:
            return []
        calls = [self.runtime_client.send_request(
            target_grain=GrainId.system_target(type_code_of(SILO_CONTROL), a),
            grain_class=SiloControl, interface_name=SILO_CONTROL,
            method_name="ctl_trace_spans", args=(trace_id,),
            kwargs={"pull": True},
            target_silo=a, category=Category.SYSTEM, timeout=1.0)
            for a in peers]
        results = await asyncio.gather(*calls, return_exceptions=True)
        # cross-process span-level dedup: worker-process silos make the
        # duplicate pull real — a leg that was forwarded (or a span a
        # peer itself pulled and retained) can come back from more than
        # one silo in this fan-out, and export must not double-count it
        out: list[dict] = []
        seen: set = set()
        for r in results:
            if not isinstance(r, BaseException) and r:
                for d in r:
                    sid = d.get("span_id")
                    if sid is not None:
                        if sid in seen:
                            continue
                        seen.add(sid)
                    out.append(d)
        return out

    def _install_loop_profiler(self, loop) -> None:
        """Install (or join) the loop's occupancy profiler and wire this
        silo's consumers: per-category occupancy gauges, the dispatcher/
        engine/storage category hooks, the tail-retention flight trigger,
        and the telemetry sink hook. Co-hosted silos on one loop share
        one profiler (occupancy is a loop property); install is
        refcounted, so the last silo to stop removes the interposition."""
        from ..observability.profiling import (LOOP_CATEGORIES,
                                               install_loop_profiler)
        cfg = self.config
        lp = install_loop_profiler(
            loop, window=cfg.profiling_window, ring=cfg.profiling_ring,
            top_k=cfg.profiling_top_k,
            trigger_interval=cfg.profiling_trigger_interval)
        self.loop_prof = lp
        # cached refs so the hot paths pay one attribute load
        self.dispatcher._loop_prof = lp
        self.storage_manager.loop_prof = lp
        if hasattr(self.fabric, "loop_prof"):
            # socket fabric: the inline client-route encode+write books
            # its slice under "egress" (the sharded-egress A/B signal)
            self.fabric.loop_prof = lp
        if self.vector is not None:
            self.vector.loop_prof = lp
        for cat in LOOP_CATEGORIES:
            # live per-category occupancy of the LAST completed window
            # (the Prometheus gauges; cumulative shares ride ctl_loop_profile)
            self.stats.register_gauge(
                f"loop.occupancy.{cat}",
                lambda c=cat, p=lp: p.last_shares.get(c, 0.0))
        if self.tracer is not None:
            # tail-retained traces snapshot the flight recorder and stamp
            # the root span so the retained trace links to its loop state
            def _retained(root, reason, _lp=lp):
                snap = _lp.trigger(
                    "trace_retained", reason=reason,
                    trace_id=(f"{root.trace_id:x}"
                              if root is not None else None))
                if snap is not None and root is not None:
                    root.attrs = dict(root.attrs or {})
                    root.attrs["flight_snapshot"] = True
            self.tracer.on_retain = _retained
        tm = getattr(self, "telemetry", None)
        if tm is not None:
            # flight snapshots also land as telemetry events (the
            # "attach it to the telemetry sink" half of the recorder)
            def _hook(snap, _tm=tm):
                _tm.track_event("flight_recorder", reason=snap["reason"],
                                **snap["attrs"])
            self._flight_hook = _hook
            lp.trigger_hooks.append(_hook)

    def register_system_target(self, instance, name: str) -> GrainId:
        """Register a per-silo pseudo-grain at a well-known id
        (SystemTarget framework, Silo.RegisterSystemTarget Silo.cs:816-820).
        The instance's public async methods become remotely callable with
        ``target_silo`` pinned to this silo."""
        from ..core.ids import type_code_of
        from .activation import ActivationData, ActivationState
        gid = GrainId.system_target(type_code_of(name), self.silo_address)
        act = ActivationData(gid, self, type(instance))
        act.state = ActivationState.VALID
        act.grain_instance = instance
        instance._activation = act
        self.catalog.by_activation[act.activation_id] = act
        self.catalog.by_grain[gid] = [act]
        return gid

    # helper used by Catalog to run lifecycle hooks in activation context
    async def dispatcher_scoped(self, activation, coro_fn) -> None:
        token = current_activation.set(activation)
        try:
            await coro_fn()
        finally:
            current_activation.reset(token)

    def __repr__(self) -> str:
        return f"<Silo {self.silo_address} {self.status}>"


class SiloBuilder:
    """Fluent hosting builder (SiloHostBuilder.cs:13)."""

    def __init__(self) -> None:
        self.config = SiloConfig()
        self.registry = GrainRegistry()
        self.storage = StorageManager()
        self._fabric: "InProcFabric | None" = None
        self._configurators: list[Callable[[Silo], None]] = []

    def with_name(self, name: str) -> "SiloBuilder":
        self.config.name = name
        return self

    def with_config(self, **kw) -> "SiloBuilder":
        for k, v in kw.items():
            if not hasattr(self.config, k):
                raise AttributeError(f"unknown silo option {k!r}")
            setattr(self.config, k, v)
        return self

    def with_options(self, *groups) -> "SiloBuilder":
        """Typed options groups (the ``.Configure<XOptions>(...)`` idiom):
        ``builder.with_options(MessagingOptions(response_timeout=5))`` —
        validates each group, then overlays it on the flat config."""
        from ..config import apply_options

        apply_options(self.config, *groups)
        return self

    def add_grains(self, *grain_classes: type) -> "SiloBuilder":
        self.registry.register(*grain_classes)
        return self

    def with_storage(self, name: str, provider) -> "SiloBuilder":
        self.storage.add(name, provider)
        return self

    def with_fabric(self, fabric: "InProcFabric") -> "SiloBuilder":
        self._fabric = fabric
        return self

    def add_incoming_call_filter(self, *filters) -> "SiloBuilder":
        """AddIncomingGrainCallFilter: run ``async f(ctx)`` around every
        incoming grain invocation, in registration order
        (SiloHostBuilderGrainCallFilterExtensions analog)."""
        self._configurators.append(
            lambda silo: silo.incoming_call_filters.extend(filters))
        return self

    def add_outgoing_call_filter(self, *filters) -> "SiloBuilder":
        """AddOutgoingGrainCallFilter: run ``async f(ctx)`` around every
        outgoing call made from inside this silo."""
        self._configurators.append(
            lambda silo: silo.runtime_client.outgoing_call_filters
            .extend(filters))
        return self

    def configure(self, fn: Callable[[Silo], None]) -> "SiloBuilder":
        """Escape hatch mirroring ConfigureServices: run fn(silo) pre-start."""
        self._configurators.append(fn)
        return self

    def build(self) -> Silo:
        from .cluster import InProcFabric
        fabric = self._fabric or InProcFabric()
        silo = Silo(self.config, fabric, self.registry, self.storage)
        for fn in self._configurators:
            fn(silo)
        return silo
