"""In-process fabric + external cluster client.

The fabric plays the role of the reference's socket layer for in-process
clusters (/root/reference/src/Orleans.Core/Messaging/SocketManager.cs,
Runtime/Messaging/Gateway.cs:17, GatewayAcceptor.cs) and is the fault
injection point for liveness tests (kill = AppDomain unload in
TestingHost/AppDomainSiloHandle.cs:14; here: drop the silo from routing).

The client mirrors OutsideRuntimeClient (Core/Runtime/OutsideRuntimeClient.cs:22)
+ ClientMessageCenter/GatewayManager: gateway selection is round-robin over
alive silos; responses route back via the client's pseudo silo address.
"""

from __future__ import annotations

import itertools
import logging
from typing import Any

from ..core.errors import SiloUnavailableError
from ..core.ids import SiloAddress
from ..core.message import Direction, Message
from .hotlane import try_hot_invoke as _hot_invoke
from .references import GrainFactory
from .runtime_client import RuntimeClient

log = logging.getLogger("orleans.fabric")

__all__ = ["InProcFabric", "ClusterClient"]


class InProcFabric:
    """Message routing + liveness simulation for every silo/client sharing
    one event loop."""

    def __init__(self) -> None:
        self.silos: dict[SiloAddress, Any] = {}
        self.clients: dict[SiloAddress, "ClusterClient"] = {}
        self.dead: set[SiloAddress] = set()
        self._alive_cache: list[SiloAddress] | None = None
        self._ports = itertools.count(11111)
        self._generation = itertools.count(1)
        # ordered pairs of endpoints whose traffic is dropped (partition tests)
        self.partitions: set[tuple[str, str]] = set()

    # -- address allocation ---------------------------------------------
    def allocate_address(self, name: str) -> SiloAddress:
        return SiloAddress(name, next(self._ports), next(self._generation))

    def allocate_client_address(self) -> SiloAddress:
        return SiloAddress("client", next(self._ports), next(self._generation))

    # -- membership of the wire (not the cluster oracle) ------------------
    def register_silo(self, silo) -> None:
        self.silos[silo.silo_address] = silo
        self.dead.discard(silo.silo_address)
        self._alive_cache = None
        self._broadcast_membership()

    def unregister_silo(self, silo, dead: bool = False) -> None:
        self.silos.pop(silo.silo_address, None)
        if dead:
            self.dead.add(silo.silo_address)
        self._alive_cache = None
        self._broadcast_membership(dead=[silo.silo_address] if dead else [])

    def invalidate_alive_cache(self) -> None:
        """Called on silo status transitions (e.g. Running→ShuttingDown)
        that change gateway eligibility without (un)registration."""
        self._alive_cache = None

    def _broadcast_membership(self, dead: list[SiloAddress] | None = None) -> None:
        """Fan membership changes to every silo's locator. When a membership
        oracle is installed on the silos, the oracle drives these
        notifications instead (probe/vote protocol) and the fabric only
        carries the wire."""
        alive = self.alive_silos()
        for s in list(self.silos.values()):
            if s.membership is None:
                s.locator.on_membership_change(alive, dead or [])
                if dead:
                    for d in dead:
                        s.runtime_client.break_outstanding_to_dead_silo(d)

    def register_client(self, client: "ClusterClient") -> None:
        self.clients[client.silo_address] = client

    def unregister_client(self, client: "ClusterClient") -> None:
        self.clients.pop(client.silo_address, None)

    def is_dead(self, addr: SiloAddress) -> bool:
        # dead ⊆ unregistered (unregister_silo removes + marks), so one
        # membership test decides
        return not (addr in self.silos or addr in self.clients)

    def alive_silos(self) -> list[SiloAddress]:
        cached = self._alive_cache
        if cached is None:
            cached = self._alive_cache = [
                a for a, s in self.silos.items()
                if s.status in ("Running", "Joining")]
        return cached

    # -- fault injection --------------------------------------------------
    def partition(self, a: SiloAddress, b: SiloAddress) -> None:
        self.partitions.add((a.endpoint, b.endpoint))
        self.partitions.add((b.endpoint, a.endpoint))

    def heal_partition(self, a: SiloAddress, b: SiloAddress) -> None:
        self.partitions.discard((a.endpoint, b.endpoint))
        self.partitions.discard((b.endpoint, a.endpoint))

    # -- the wire ----------------------------------------------------------
    def deliver(self, msg: Message) -> None:
        """Route one message to its target silo or client inbox."""
        target = msg.target_silo
        if target is None:
            log.warning("dropping unaddressed message %s", msg.method_name)
            return
        if msg.sending_silo is not None and \
                (msg.sending_silo.endpoint, target.endpoint) in self.partitions:
            return  # partitioned: silently dropped, like a black-holed link
        client = self.clients.get(target)
        if client is not None:
            client.deliver(msg)
            return
        silo = self.silos.get(target)
        if silo is None or target in self.dead:
            return  # dead silo: dropped; senders learn via membership/timeout
        silo.message_center.deliver(msg)

    def deliver_via_gateway(self, gateway: SiloAddress, msg: Message) -> None:
        """Client ingress: hand to a gateway silo which will address it
        (GatewayAcceptor path)."""
        silo = self.silos.get(gateway)
        if silo is None:
            raise SiloUnavailableError(f"gateway {gateway} unavailable")
        silo.message_center.deliver(msg)

    def deliver_group(self, target: SiloAddress, msgs: list) -> None:
        """Batched outbound hand-off for ONE destination
        (``MessageCenter.send_batch`` — the batched-egress response
        path): a client gets one ``deliver_batch`` correlation pass, a
        silo one ``deliver_batch`` routing hop."""
        if target is None:
            log.warning("dropping %d unaddressed batched messages",
                        len(msgs))
            return
        first = msgs[0]
        if first.sending_silo is not None and \
                (first.sending_silo.endpoint,
                 target.endpoint) in self.partitions:
            return  # one sender, one target: the whole group is cut
        client = self.clients.get(target)
        if client is not None:
            client.deliver_batch(msgs)
            return
        silo = self.silos.get(target)
        if silo is None or target in self.dead:
            return  # dead silo: dropped, like deliver()
        silo.message_center.deliver_batch(msgs)

    def deliver_via_gateway_batch(self, gateway: SiloAddress,
                                  msgs: list) -> None:
        """Batched client ingress (``ClusterClient.transmit_batch``): one
        group → one ``deliver_batch`` routing hop on the gateway silo —
        the in-proc twin of a gateway socket read decoding a whole wire
        batch."""
        silo = self.silos.get(gateway)
        if silo is None:
            raise SiloUnavailableError(f"gateway {gateway} unavailable")
        silo.message_center.deliver_batch(msgs)


class ClusterClient(RuntimeClient):
    """External client (OutsideRuntimeClient.cs:22): N gateway connections →
    here, round-robin gateway pick per request over alive silos."""

    def __init__(self, fabric: InProcFabric, response_timeout: float = 30.0):
        super().__init__(response_timeout=response_timeout)
        self.fabric = fabric
        self._address = fabric.allocate_client_address()
        self.grain_factory = GrainFactory(self)
        self._gateway_rr = 0
        self.connected = False
        # hot-lane locality hint: grain_id → hosting SiloAddress
        # (re-resolved through fabric.silos and re-verified against the
        # silo's catalog on every use, so a stale entry just re-resolves
        # and a dead silo is never pinned; bounded so key-churn workloads
        # can't grow it)
        self._hot_silo_cache: dict = {}
        from .observers import ObserverHost
        self._observer_host = ObserverHost(lambda: self._address)

    # -- RuntimeClient surface --------------------------------------------
    @property
    def silo_address(self) -> SiloAddress:
        return self._address

    def try_hot_invoke(self, grain_id, grain_class: type,
                       interface_name: str, method_name: str,
                       args: tuple, kwargs: dict,
                       is_read_only: bool = False):
        """Hot lane for the in-proc fabric: every silo shares this event
        loop, so a call whose activation lives in ANY registered silo is
        "local" in the hot-lane sense.  Gateway-only semantics that the
        lane would bypass force a fallback: load shedding (queue depth is
        the shed signal) and non-Running silos.  Socket-backed clients
        (multiprocess clusters) never take this path — their fabric holds
        no silo objects."""
        if not self.hot_lane_enabled or not self.connected:
            return None
        cache = self._hot_silo_cache
        addr = cache.get(grain_id)
        # the hint stores the ADDRESS, not the silo object: a killed silo
        # leaves fabric.silos, so a stale hint resolves to None here and
        # can never pin a dead silo's catalog/activations in memory
        silo = self.fabric.silos.get(addr) if addr is not None else None
        if silo is None or silo.status != "Running" or \
                not silo.catalog.by_grain.get(grain_id):
            # a non-gracefully killed silo keeps its catalog populated, so
            # the status is part of hint validity — a dead hint re-resolves
            # (the grain reactivates elsewhere) instead of pinning the
            # fallback path forever
            silo = None
            for s in self.fabric.silos.values():
                if s.status == "Running" and s.catalog.by_grain.get(grain_id):
                    silo = s
                    break
            if silo is None:
                cache.pop(grain_id, None)  # never retain a dead hint
                self.hot_fallbacks += 1
                return None
            if len(cache) >= 65536:
                cache.clear()
            cache[grain_id] = silo.silo_address
        if silo.config.load_shedding_enabled:
            self.hot_fallbacks += 1
            return None
        coro = _hot_invoke(self, silo, grain_id, grain_class,
                           interface_name, method_name,
                           args, kwargs, is_read_only)
        if coro is None:
            self.hot_fallbacks += 1
        else:
            self.hot_hits += 1
        return coro

    def _pick_gateway(self, msg: Message, gateways: list) -> SiloAddress:
        """The ONE affinity rule for both transmit paths: route by
        target-grain hash so one grain's requests keep order through one
        gateway (ClientMessageCenter affinity routing), round-robin for
        untargeted traffic."""
        if msg.target_grain is not None:
            return gateways[msg.target_grain.uniform_hash % len(gateways)]
        self._gateway_rr = (self._gateway_rr + 1) % len(gateways)
        return gateways[self._gateway_rr]

    def transmit(self, msg: Message) -> None:
        msg.sending_silo = self._address
        self._mark_remote_trace(msg)  # client sends always leave the client
        gateways = self.fabric.alive_silos()
        if not gateways:
            raise SiloUnavailableError("no gateways available")
        self.fabric.deliver_via_gateway(self._pick_gateway(msg, gateways),
                                        msg)

    def transmit_batch(self, msgs: list) -> None:
        """Batched transmit (RuntimeClient.call_batch): the group is
        split per gateway by the same affinity rule as ``transmit``
        (shared ``_pick_gateway``) and each gateway's slice rides ONE
        ``deliver_batch`` hop."""
        gateways = self.fabric.alive_silos()
        if not gateways:
            raise SiloUnavailableError("no gateways available")
        groups: dict[SiloAddress, list] = {}
        for msg in msgs:
            msg.sending_silo = self._address
            self._mark_remote_trace(msg)
            groups.setdefault(self._pick_gateway(msg, gateways),
                              []).append(msg)
        for gw, batch in groups.items():
            try:
                self.fabric.deliver_via_gateway_batch(gw, batch)
            except Exception as e:  # noqa: BLE001 — one gateway's slice:
                # earlier slices were already delivered and will execute,
                # so this must NOT raise (the caller would unregister
                # their callbacks too) — fail exactly this slice
                self._fail_transmit(batch, e)

    def deliver(self, msg: Message) -> None:
        """Inbound from the fabric (the client message pump,
        OutsideRuntimeClient.RunClientMessagePump:235)."""
        if msg.direction == Direction.RESPONSE:
            self.receive_response(msg)
        elif self._observer_host.dispatch(msg):
            pass  # grain→client observer notification
        else:
            log.debug("client dropping unexpected message %s",
                      msg.method_name)

    def add_outgoing_call_filter(self, *filters) -> "ClusterClient":
        """AddOutgoingGrainCallFilter, client side (ClientBuilder analog):
        filters wrap every call this client sends."""
        self.outgoing_call_filters.extend(filters)
        return self

    # -- observers (CreateObjectReference / DeleteObjectReference) ---------
    def create_observer(self, obj):
        return self._observer_host.create_observer(obj)

    def delete_observer(self, ref) -> bool:
        return self._observer_host.delete_observer(ref)

    # -- lifecycle ---------------------------------------------------------
    async def connect(self) -> "ClusterClient":
        if not self.fabric.alive_silos():
            raise SiloUnavailableError("no silos to connect to")
        self.fabric.register_client(self)
        self.connected = True
        return self

    async def close_async(self) -> None:
        self.fabric.unregister_client(self)
        self.connected = False
        self.close()

    def get_grain(self, grain_class: type, key, key_ext: str | None = None):
        return self.grain_factory.get_grain(grain_class, key, key_ext)
