"""Per-(grain_class, method) invoker table — the IL-emitted-invoker analog.

The reference compiles one invoker per grain method at build time
(/root/reference/src/Orleans.CodeGeneration/GrainMethodInvokerGenerator.cs,
``ILSerializerGenerator.cs``) so a hot call does a method-id switch instead
of reflection.  Python's analog: resolve everything resolvable ONCE per
(grain class, silo filter-state) — the unbound method object, its
concurrency flags, and the fused incoming-filter chain — so a hot call is
dict-lookup + gate-check + await instead of per-turn ``getattr`` walks and
chain rebuilds (the join-calculus "compile the match ahead of time" move,
arxiv 1302.6329).

Invalidation: entries revalidate on every lookup against two cheap tokens —
the silo's incoming-filter count (filter registration, including direct
``silo.incoming_call_filters.append`` mutation by tests) and the class's
``__orleans_version__`` (version bump).  A stale entry rebuilds in place;
there is no explicit flush API to forget to call.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from .grain import Grain, remote_methods

if TYPE_CHECKING:
    from .silo import Silo

__all__ = ["MethodInvoker", "ClassInvokers", "InvokerTable"]


class MethodInvoker:
    """One remote method, flags pre-resolved (the codegen'd proxy body)."""

    __slots__ = ("name", "fn", "is_read_only", "is_always_interleave",
                 "is_one_way")

    def __init__(self, name: str, fn):
        self.name = name
        self.fn = fn  # unbound: called as fn(instance, *args, **kwargs)
        self.is_read_only = getattr(fn, "__orleans_read_only__", False)
        self.is_always_interleave = getattr(
            fn, "__orleans_always_interleave__", False)
        self.is_one_way = getattr(fn, "__orleans_one_way__", False)


class ClassInvokers:
    """Invoker set for one grain class under one silo filter-state."""

    __slots__ = ("cls", "methods", "silo_chain", "class_filtered",
                 "hot_ok", "stateless_cap", "nfilters", "version")

    def __init__(self, cls: type, silo_filters: list):
        self.cls = cls
        self.methods = {name: MethodInvoker(name, fn)
                        for name, fn in remote_methods(cls).items()}
        # fused filter chain, snapshotted (or the () "no filters" sentinel);
        # the grain-level on_incoming_call hook binds per instance at
        # invoke time, so only its presence is precomputed here
        self.silo_chain = tuple(silo_filters)
        self.class_filtered = \
            getattr(cls, "on_incoming_call", None) is not None
        # hot-lane eligibility, the class-level half: ordinary Grain
        # subclasses only (system targets / vector classes take the full
        # path), no filters of any kind. Stateless-worker replica sets
        # ARE eligible since the lane learned a cheap replica pick
        # (hotlane._pick_stateless_replica) — ``stateless_cap`` carries
        # the local replica cap so the lane serves IDLE replicas and
        # hands busy sets back to the catalog (whose least-loaded pick
        # and auto-scale semantics stay authoritative).
        self.stateless_cap = getattr(cls, "__orleans_stateless_worker__", 0)
        self.hot_ok = (not self.silo_chain
                       and not self.class_filtered
                       and isinstance(cls, type) and issubclass(cls, Grain))
        # revalidation tokens
        self.nfilters = len(silo_filters)
        self.version = getattr(cls, "__orleans_version__", 0)


class InvokerTable:
    """Per-silo cache of :class:`ClassInvokers`, built at activation-class
    registration (first activation of a class) and revalidated per lookup."""

    __slots__ = ("_silo", "_cache")

    def __init__(self, silo: "Silo"):
        self._silo = silo
        self._cache: dict[type, ClassInvokers] = {}

    def entry(self, cls: type) -> ClassInvokers:
        e = self._cache.get(cls)
        filters = self._silo.incoming_call_filters
        # revalidate by filter IDENTITY, not just count: remove-A-append-B
        # keeps the length but must still invalidate. The common no-filter
        # case short-circuits on the two int compares; the tuple compare
        # only runs when filters exist (already the slow path).
        if e is not None and e.nfilters == len(filters) and \
                e.version == getattr(cls, "__orleans_version__", 0) and \
                (e.nfilters == 0 or tuple(filters) == e.silo_chain):
            return e
        e = ClassInvokers(cls, filters)
        self._cache[cls] = e
        return e
