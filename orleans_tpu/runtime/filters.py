"""Grain call filters: the incoming/outgoing interceptor chains.

Re-design of the reference's filter machinery —
/root/reference/src/Orleans.Core/Core/GrainMethodInvoker.cs (the chain
walker: filters run in registration order, each calling
``context.Invoke()`` to proceed), wired into the invoke engine at
/root/reference/src/Orleans.Runtime/Core/InsideRuntimeClient.cs:362 and
registered via SiloHostBuilderGrainCallFilterExtensions.

A filter is any async callable ``async def f(ctx)``. Inside it:

- ``await ctx.invoke()`` proceeds down the chain (ultimately calling the
  grain method / sending the request); after it returns, ``ctx.result``
  holds the outcome and may be replaced.
- returning WITHOUT calling ``ctx.invoke()`` short-circuits: the rest of
  the chain and the call itself never run; ``ctx.result`` (default None)
  is the caller-visible result.
- raising propagates to the caller as the call's failure (and unwinds
  through outer filters, which may catch and substitute a result).

Grain classes may define ``async def on_incoming_call(self, ctx)`` — it
runs as the LAST incoming filter (the reference's grain-implements-
IIncomingGrainCallFilter form, GrainMethodInvoker.cs adds the grain as
the final element of its chain).
"""

from __future__ import annotations

from typing import Any, Awaitable, Callable, Sequence

from ..core.ids import GrainId

__all__ = [
    "GrainCallContext",
    "IncomingCallContext",
    "OutgoingCallContext",
    "run_call_chain",
]

GrainCallFilter = Callable[["GrainCallContext"], Awaitable[None]]


class GrainCallContext:
    """Shared surface of IIncoming/IOutgoingGrainCallContext: the method
    identity, mutable arguments, and the mutable result."""

    __slots__ = ("interface_name", "method_name", "args", "kwargs",
                 "result", "_chain", "_terminal", "_next")

    def __init__(self, chain: Sequence[GrainCallFilter], terminal,
                 interface_name: str, method_name: str,
                 args: tuple, kwargs: dict):
        self.interface_name = interface_name
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs
        self.result: Any = None
        self._chain = chain
        self._terminal = terminal
        self._next = 0

    async def invoke(self) -> None:
        """Proceed to the next filter (or, past the end of the chain, the
        call itself). Mirrors GrainMethodInvoker.Invoke's index walk: a
        filter calling ``invoke()`` more than once over-advances the index
        and is rejected — double-invocation would run the grain method
        twice."""
        i = self._next
        self._next = i + 1
        if i < len(self._chain):
            await self._chain[i](self)
        elif i == len(self._chain):
            self.result = await self._terminal(self)
        else:
            raise RuntimeError(
                f"grain call filter invoked ctx.invoke() more than once "
                f"for {self.interface_name}.{self.method_name}")


class IncomingCallContext(GrainCallContext):
    """Silo-side view: the target activation's instance is in hand."""

    __slots__ = ("grain", "grain_id")

    def __init__(self, chain, terminal, *, grain: Any, grain_id: GrainId,
                 interface_name: str, method_name: str,
                 args: tuple, kwargs: dict):
        super().__init__(chain, terminal, interface_name, method_name,
                         args, kwargs)
        self.grain = grain
        self.grain_id = grain_id


class OutgoingCallContext(GrainCallContext):
    """Caller-side view: only the target identity exists yet."""

    __slots__ = ("grain_class", "target_grain")

    def __init__(self, chain, terminal, *, grain_class: type,
                 target_grain: GrainId, interface_name: str,
                 method_name: str, args: tuple, kwargs: dict):
        super().__init__(chain, terminal, interface_name, method_name,
                         args, kwargs)
        self.grain_class = grain_class
        self.target_grain = target_grain


async def run_call_chain(ctx: GrainCallContext) -> Any:
    """Run the whole chain from the top and return the final result."""
    await ctx.invoke()
    return ctx.result
