"""Hot lane: frame-collapsed dispatch for local grain calls.

The r5 attribution (benchmarks/BENCH_r05_ping_attribution.json) showed the
host tier capped at ~43k calls/sec against a 129-175k bare-asyncio ceiling,
with the gap being the ~40 Python frames of full messaging semantics per
call — resolve → Message → queue → turn task → callback → response-route —
not any single component.  This module collapses that pipeline for the
dominant case (a local, Valid, gate-admitting activation with nothing
special in flight) into a handful of frames: dict lookups, a gate check,
and a direct await of the grain method, resolving the caller directly with
no ``Message``, no ``CallbackData``, and no timeout-sweeper entry.  It
generalizes ``InsideRuntimeClient.try_direct_interleave`` (which covered
only always-interleave methods) into the default in-silo path.

Anything complicated falls back to the untouched full messaging path, so
the hot lane never has to replicate rare-path semantics:

* no local single Valid activation (remote, activating, deactivating,
  migration-fenced, stateless-worker replica set, duplicate race);
* the reentrancy gate does not admit the call (busy non-reentrant
  activation) — the messaging path enqueues it in arrival order, so hot
  calls can never reorder ahead of queued turns;
* any call filter is registered (outgoing, silo incoming, or a grain-level
  ``on_incoming_call`` hook) — interception fires identically regardless
  of placement;
* tracing actually sampled this call — the lane rolls the head-sample die
  itself (collector installed with a non-zero rate) and hands a winning
  roll to the messaging path via ``SpanCollector.presampled``, so at
  sample rates ≪1 only the sampled minority leaves the lane and sampled
  traces keep their intact span tree; an ambient trace context to
  propagate always falls back;
* ambient RequestContext baggage, including a transaction context — the
  header round-trip (TransactionInfo piggyback) only exists on the
  messaging path;
* a cancellation token argument — token target bookkeeping rides the send
  path;
* an explicit per-call timeout / armed expiry (grain references never pass
  one today, so this is structural: hot calls rely on the stuck-activation
  watchdog, exactly like the direct-interleave path always has).

The ``DISPATCH_STATS`` counter pair (observability.stats) makes the
hit/fallback ratio observable: plain int fields on the client (a registry
increment per call was itself measurable in the attribution), surfaced as
gauges on the silo's StatsRegistry and in the ping benchmark ``extra``.
"""

from __future__ import annotations

import asyncio
import itertools
import time
from typing import TYPE_CHECKING

from ..core.serialization import copy_call_body, copy_result
from ..observability.tracing import current_trace
from .activation import ActivationState
from .cancellation import GrainCancellationToken
from .context import _request_context, current_activation, current_call_chain

if TYPE_CHECKING:
    from .activation import ActivationData
    from .silo import Silo

__all__ = ["try_hot_invoke", "HotTurnMarker"]


class HotTurnMarker:
    """Pooled stand-in for a Message in ``ActivationData.running`` while a
    hot-lane turn executes: enough surface for the reentrancy gate
    (``is_read_only``), call-chain building (``call_chain``), and the
    stuck-activation probe (``id`` keyed into ``running_since``).  This is
    the "pooled context" of the inline turn — acquired from a freelist,
    released when the turn ends."""

    __slots__ = ("id", "call_chain", "is_read_only")

    def __init__(self, id: int, call_chain: tuple, is_read_only: bool):
        self.id = id
        self.call_chain = call_chain
        self.is_read_only = is_read_only


_MARKER_POOL: list[HotTurnMarker] = []
_MARKER_POOL_CAP = 256
# forced-yield cadence when the loop has nothing else ready (see the
# batch-aware fairness note at the end of _hot_turn)
_HOT_YIELD_EVERY = 64
# ONE sequence of negative ids for every running-marker kind (hot-lane
# markers here AND silo._DirectCallMarker): negative so they can never
# collide with wire message ids in an activation's running_since map, and
# shared so two marker kinds concurrently running on one activation can
# never collide with each other (which would blind the stuck-activation
# probe to whichever turn lost the running_since entry).
marker_ids = itertools.count(1)


def _acquire_marker(chain: tuple, read_only: bool) -> HotTurnMarker:
    mid = -next(marker_ids)
    pool = _MARKER_POOL
    if pool:
        m = pool.pop()
        m.id = mid
        m.call_chain = chain
        m.is_read_only = read_only
        return m
    return HotTurnMarker(mid, chain, read_only)


def _release_marker(m: HotTurnMarker) -> None:
    if len(_MARKER_POOL) < _MARKER_POOL_CAP:
        m.call_chain = ()
        _MARKER_POOL.append(m)


def _gate_admits(act: "ActivationData", inv, is_read_only: bool,
                 grain_id, chain: tuple) -> bool:
    """Inline reentrancy gate (Dispatcher.CanInterleave): a refusal means
    the messaging path will ENQUEUE the call behind the running turn — so
    a hot-lane fallback on refusal preserves arrival order exactly."""
    if not act.running:
        return True
    return (act.is_reentrant or inv.is_always_interleave
            or (is_read_only and all(m.is_read_only for m in act.running))
            or grain_id in chain)


def _pick_stateless_replica(acts):
    """Cheap replica choice for a StatelessWorker set (the ROADMAP
    carry-over): the first VALID replica with nothing running — an idle
    replica trivially admits, so no gate walk or load compare is needed.
    None when every replica is busy/transitioning: the call then takes
    the messaging path, where the catalog's least-loaded pick and
    auto-scale (maybe_add_stateless_replica) stay authoritative — the
    lane never grows or queues on a replica set itself."""
    for a in acts:
        if a.state is ActivationState.VALID and not a.running:
            return a
    return None


def try_hot_invoke(client, silo: "Silo", grain_id, grain_class: type,
                   interface_name: str, method_name: str,
                   args: tuple, kwargs: dict, is_read_only: bool):
    """Gate-check a local call for the hot lane.  Returns the inline-turn
    coroutine on admission, None to take the messaging path.  ``client``
    is the RuntimeClient the call originates from (its filters/tracer
    gate the lane; its counters record the outcome)."""
    acts = silo.catalog.by_grain.get(grain_id)
    if not acts:
        return None
    act = acts[0]
    entry = silo.invokers.entry(act.grain_class)
    if not entry.hot_ok or client.outgoing_call_filters:
        return None
    if entry.stateless_cap:
        # StatelessWorker: serve an idle replica inline, hand busy sets
        # to the messaging path (catalog replica pick + auto-scale)
        act = _pick_stateless_replica(acts)
        if act is None:
            return None
    elif len(acts) != 1:
        return None  # duplicate-activation race on a single-activation grain
    if act.state is not ActivationState.VALID:
        return None  # activating/deactivating/migration-fenced/invalid
    inv = entry.methods.get(method_name)
    if inv is None or inv.is_one_way:
        return None
    # per-INSTANCE shadowing: a hook or method attached to the instance
    # (fault injection, grain-level gate set in __init__) is invisible to
    # the class-level table — the messaging path resolves both, so decline
    instance = act.grain_instance
    d = getattr(instance, "__dict__", None)
    if d is not None and (method_name in d or "on_incoming_call" in d):
        return None
    if current_trace.get() is not None:
        return None  # continuing a sampled trace: headers must propagate
    if _request_context.get():
        return None  # baggage/txn context rides message headers
    for a in args:
        if type(a) is GrainCancellationToken:
            return None
    if kwargs:
        for a in kwargs.values():
            if type(a) is GrainCancellationToken:
                return None
    # caller chain (deadlock/reentrancy bookkeeping — the same shared
    # construction as the messaging send path)
    chain = current_call_chain()
    if not _gate_admits(act, inv, is_read_only, grain_id, chain):
        return None
    tracer = client.tracer
    if tracer is not None and tracer.sample_rate > 0:
        # sampled-trace hot lane: roll the head-sample die HERE instead of
        # declining whenever a collector is installed — at sample rates
        # ≪1 the lane keeps serving the unsampled majority and only the
        # sampled minority pays the messaging path. The roll is handed to
        # send_request via the collector's one-shot ``presampled`` slot
        # (consumed synchronously in this same step), so the effective
        # rate stays exactly ``sample_rate``, never its square. Rolled
        # LAST, after every other decline: a call the lane turns away for
        # a different reason must reach the messaging path un-rolled, or
        # its record probability would double.
        if tracer.sample():
            tracer.presampled = True
            return None
    return _hot_turn(client, silo, act, inv, grain_id, grain_class,
                     interface_name, args, kwargs, is_read_only, chain,
                     tracer)


async def _hot_turn(client, silo: "Silo", act: "ActivationData", inv,
                    grain_id, grain_class: type, interface_name: str,
                    args: tuple, kwargs: dict, is_read_only: bool,
                    chain: tuple, admitted_tracer):
    """The collapsed turn: copy-isolate, run gated on a pooled running
    marker, copy-isolate the result, pump, once-per-RPC fairness yield.
    Error semantics match the messaging path (the grain's exception object
    reaches the caller; InconsistentState still triggers rebuild); the
    per-call timeout is intentionally absent (the stuck-activation
    watchdog observes via the running marker)."""
    # Re-verify admission at EXECUTION time: the gate decision above ran
    # synchronously when the caller built the coroutine, but a deferred
    # start (ensure_future/gather) executes it later — by which time the
    # activation may be migration-fenced or mid-turn, a filter/tracer may
    # have been registered, or an instance-level hook attached.  The
    # messaging path resolves ALL of those at dispatch time, so a stale
    # admission hands the call over rather than running it inline with
    # creation-time semantics.  (For the dominant ``await ref.method()``
    # shape the coroutine starts synchronously inside the caller's await,
    # so this re-check sees exactly what the gate just saw.)
    instance = act.grain_instance
    d = getattr(instance, "__dict__", None)
    tracer = client.tracer
    # tracer re-verify: admission already rolled (and lost) the sampling
    # die against ``admitted_tracer``, so running inline IS the unsampled
    # outcome — re-rolling here would skew the rate. Only a collector
    # INSTALLED/SWAPPED since admission (which never got a roll) forces
    # the messaging path, preserving the old install-after-creation guard.
    if (act.state is not ActivationState.VALID
            or not silo.invokers.entry(act.grain_class).hot_ok
            or client.outgoing_call_filters
            or (tracer is not admitted_tracer and tracer is not None
                and tracer.sample_rate > 0)
            or current_trace.get() is not None
            or (d is not None and (inv.name in d or "on_incoming_call" in d))
            or not _gate_admits(act, inv, is_read_only, grain_id, chain)):
        client.hot_hits -= 1
        client.hot_fallbacks += 1
        if (tracer is admitted_tracer and tracer is not None
                and tracer.sample_rate > 0
                and not client.outgoing_call_filters
                and current_trace.get() is None):
            # admission already rolled (and lost) the head-sample die for
            # this call — hand the UNSAMPLED outcome over too, or the
            # messaging path would re-roll and double this call class's
            # record probability. Skipped when filters appeared since
            # (their deferred send consumes the slot in a later task,
            # where it could suppress a different call's roll).
            tracer.presampled = False
        # send_request, not _send_request_unfiltered: an outgoing filter
        # registered since coroutine creation must wrap this call too
        return await client.send_request(
            target_grain=grain_id, grain_class=grain_class,
            interface_name=interface_name, method_name=inv.name,
            args=args, kwargs=kwargs, is_read_only=is_read_only,
            is_always_interleave=inv.is_always_interleave)
    args, kwargs = copy_call_body(args, kwargs)
    ctx_token = None
    if _request_context.get() is not None:
        # the caller attached baggage AFTER building the call coroutine;
        # the messaging path captures headers at call time (when the
        # context was empty — the gate checked), so the callee must not
        # see it — and the caller must get it back afterwards
        ctx_token = _request_context.set(None)
    marker = _acquire_marker(chain, is_read_only)
    act.record_running(marker)
    token = current_activation.set(act)
    # cost attribution (observability.ledger): an inline turn is exec
    # only — it never queued, and the lane declined any baggage-carrying
    # call above, so tenancy comes from the tenant_of hook alone. The
    # clock is read only when a ledger is installed (the disabled lane
    # pays one attribute load).
    led = silo.ledger
    t_led = time.monotonic() if led is not None else 0.0
    try:
        result = copy_result(await inv.fn(act.grain_instance,
                                          *args, **kwargs))
    except asyncio.CancelledError:
        raise
    except BaseException as e:
        silo.catalog.on_invoke_error(act, e)
        raise
    finally:
        if led is not None:
            led.charge_turn(
                interface_name, inv.name, time.monotonic() - t_led,
                key=f"{act.grain_class.__name__}/{grain_id.key}")
        current_activation.reset(token)
        if ctx_token is not None:
            _request_context.reset(ctx_token)  # restore caller baggage
        elif _request_context.get() is not None:
            # the callee set baggage during the inline turn; the messaging
            # path clears turn-local context, so must we (the caller's own
            # context was None — a hot call never admits ambient baggage)
            _request_context.set(None)
        act.reset_running(marker)
        _release_marker(marker)
        # messages that arrived during the call queued behind the running
        # marker; nothing else pumps them for an inline turn
        silo.dispatcher.run_message_pump(act)
    # Batch-aware fairness yield — the liveness contract the messaging
    # path enforces in RuntimeClient._await_response, minus its tax when
    # it buys nothing.  The old once-per-RPC unconditional sleep(0) cost
    # ~30% of the collapsed turn's headroom; yielding is only USEFUL when
    # the event loop actually has other ready callbacks to run (a starved
    # ticker task, a queued turn, a completed IO wakeup).  So: yield when
    # the loop's ready queue is non-empty (our own frame was popped off it
    # before running, so anything in it is someone else), and otherwise
    # force one yield every _HOT_YIELD_EVERY collapsed turns — timer
    # callbacks (membership probes, reminders) sit in the SCHEDULED heap,
    # not the ready queue, and only migrate across a loop iteration, so a
    # ready-queue check alone would re-open the starvation hazard
    # test_tight_call_loop guards (the bound keeps it to ~64 sub-30µs
    # turns, far under any probe period).  Loops without a _ready deque
    # (non-CPython event loops) keep the per-call yield.
    ready = getattr(asyncio.get_running_loop(), "_ready", None)
    client.hot_calls_since_yield += 1
    if ready is None or ready or \
            client.hot_calls_since_yield >= _HOT_YIELD_EVERY:
        client.hot_calls_since_yield = 0
        await asyncio.sleep(0)
    return result
