"""TCP socket fabric: cross-process silo-to-silo transport + client gateway.

Re-design of the reference's socket layer
(/root/reference/src/Orleans.Core/Messaging/SocketManager.cs:1-261,
``IncomingMessageAcceptor.cs:12`` accept/receive loop,
``OutboundMessageQueue.cs:38-44`` per-target senders,
``Runtime/Messaging/Gateway.cs:17`` + ``GatewayAcceptor.cs`` client ingress,
``Core/Messaging/ClientMessageCenter.cs:63`` + ``GatewayManager.cs`` client
side) for silos living in **separate processes/hosts**.

Architecture (departures from the reference are deliberate):

* One asyncio TCP server per silo accepts both peer-silo and client
  connections; the first frame is a handshake declaring the peer kind and
  address (GatewayAcceptor.cs:63 handshake-carried client id analog).
* Outbound: one lazily-dialed connection + send queue per target endpoint
  (the reference hashes targets over N sender threads; one asyncio sender
  task per endpoint gives the same per-target FIFO order without threads).
* Clients are addressed *via their gateway*: a client's pseudo
  ``SiloAddress`` carries the gateway's host:port and a client-unique
  generation, so any silo can reply by dialing the gateway, which forwards
  over the client's live connection (``Gateway.TryDeliverToProxy:229``).
* This fabric carries the **control plane and host-tier grain calls**. The
  vectorized data plane rides device collectives over ICI
  (orleans_tpu.parallel.transport) and never touches these sockets.

In-process clusters and liveness tests keep using
orleans_tpu.runtime.cluster.InProcFabric; this module exists for real
multi-process deployments and is exercised by tests over localhost sockets.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import random
import socket
import time
from typing import TYPE_CHECKING, Any

from ..core import serialization as _ser
from ..core.asyncs import ExponentialBackoff, retry
from ..core.errors import SiloUnavailableError
from ..core.ids import SiloAddress
from ..core.message import Category, Direction, Message, recycle_messages
from ..observability.stats import EGRESS_STATS
from .references import GrainFactory
from .runtime_client import RuntimeClient
from .wire import (
    FrameError,
    WireDecodeError,
    _BodyDecodeError,
    decode_frames,
    decode_handshake,
    decode_message,
    encode_handshake,
    encode_message,
    encode_message_batch,
    frame_stream,
    leads_hostile_frame,
    read_frame,
    writev_leftover,
)

if TYPE_CHECKING:
    from .silo import Silo

log = logging.getLogger("orleans.socket")

# _relay_endpoint's "not a relay case" marker (None means "consumed")
_NO_RELAY = object()

__all__ = ["SocketFabric", "GatewayClient"]

_CONNECT_RETRIES = 3
_CONNECT_BACKOFF = 0.2
# greedy sender batching: everything queued when the writer wakes rides
# one socket write (bounded so one slow peer cannot hold a huge buffer)
_SEND_BATCH_MAX = 256

# native vectored egress (hotwire.sock_writev) for the StreamWriter-
# backed sender drains — mirrors the multiloop pump's capability probe
_HW = _ser._hotwire
_HW_WRITEV = _HW is not None and hasattr(_HW, "sock_writev")

_EG_ENCODE = EGRESS_STATS["encode"]
_EG_RING_DROPS = EGRESS_STATS["ring_drops"]

# wire-charge stamp for the sharded egress stat rings (cost
# attribution): the shard may not touch the loop-confined CostLedger,
# so byte counts ride the ring and replay in EgressShardPool._apply_stats
from ..observability.ledger import WIRE_STAMP as _LEDGER_WIRE  # noqa: E402


def _writev_stream(writer: asyncio.StreamWriter, chunks: list) -> None:
    """Vectored drain for a StreamWriter-backed sender (the silo-peer
    path previously joined + wrote through the transport; only the
    ShardWriter and gateway client-route paths were vectored). When the
    transport's buffer is empty — the steady state for a sender that
    awaits ``drain()`` per batch — the chunk list rides ONE ``writev``
    syscall on the raw socket, no ``b"".join`` copy; the unsent
    remainder (kernel buffer full), transport-buffered states, and
    non-native builds fall back to the buffered write. Ordering is
    safe: the transport has nothing queued and this sender task is the
    connection's only writer."""
    if _HW_WRITEV:
        transport = writer.transport
        sock = writer.get_extra_info("socket")
        if sock is not None and transport.get_write_buffer_size() == 0:
            try:
                sent = _HW.sock_writev(sock.fileno(), chunks)
            except (BlockingIOError, InterruptedError):
                sent = 0
            except OSError:
                # surface the failure through the transport so the
                # sender's close/reconnect semantics stay identical
                writer.write(b"".join(chunks))
                return
            rest = writev_leftover(chunks, sent)
            if rest:
                writer.write(rest)
            return
    writer.write(b"".join(chunks))


def _drain_batch(queue: "asyncio.Queue[Message]", first: Message) -> list:
    """Greedy drain: everything already queued rides one write + one
    drain (the reference's sender batches the same way — SiloMessageSender
    drains its queue per send turn)."""
    batch = [first]
    while len(batch) < _SEND_BATCH_MAX:
        try:
            batch.append(queue.get_nowait())
        except asyncio.QueueEmpty:
            break
    return batch


async def _read_frame_batches(reader: asyncio.StreamReader, ist=None,
                              ledger=None, route="", *,
                              strict_tail: bool, chunk_size: int = 1 << 16):
    """Shared chunked-receive state machine for the batched pumps (silo
    and gateway sides): one ``decode_frames`` pass per socket read,
    yielding ``(msgs, bounces)``; the partial tail of a frame stays
    buffered for the next read. Raises :class:`FrameError` when a hostile
    (oversized) announcement leads the remaining buffer — frames decoded
    ahead of it were already yielded, matching the per-frame path's
    deliver-then-drop behavior, and the link drops without waiting for
    bytes the peer may never send. EOF mid-frame raises
    ``IncompleteReadError`` under ``strict_tail`` (silo links surface the
    torn tail) or just ends the pump (gateway: a torn tail is a clean
    close)."""
    buf = bytearray()
    while True:
        chunk = await reader.read(chunk_size)
        if not chunk:
            if buf and strict_tail:
                raise asyncio.IncompleteReadError(bytes(buf), None)
            return
        buf += chunk
        consumed, msgs, bounces = decode_frames(buf, ist)
        if consumed:
            del buf[:consumed]
            if ledger is not None:
                # cost attribution: inbound bytes charged where the
                # frame sizes are already known (loop-side callers only
                # pass a ledger — the sharded pumps stamp instead)
                ledger.charge_wire(route, rx=consumed)
        if msgs or bounces:
            yield msgs, bounces
        if leads_hostile_frame(buf):
            raise FrameError("oversized frame announced")



# a peer that accepts TCP but never sends its handshake reply is wedged:
# bound the negotiation read so the dial fails into the retry/backoff path
_NEGOTIATE_TIMEOUT = 5.0


async def _read_peer_codec(reader: asyncio.StreamReader) -> bool:
    """Read the acceptor's handshake reply; True iff the peer advertises
    hotwire decode support. A well-framed but undecodable reply falls back
    to the universally-decodable pickle form; a GARBLED or truncated frame
    raises ConnectionError — the stream is misaligned and every later frame
    on it would misparse, so the dial must fail into the retry path (fresh
    connection), never keep reading. An unresponsive peer raises
    TimeoutError — an OSError — into the same path."""
    try:
        headers, _ = await asyncio.wait_for(
            read_frame(reader), _NEGOTIATE_TIMEOUT)
    except (FrameError, asyncio.IncompleteReadError) as e:
        raise ConnectionError(f"handshake reply unreadable: {e}") from e
    try:
        return bool(decode_handshake(headers).get("hotwire", False))
    except Exception:  # noqa: BLE001 — well-framed junk reply → pickle
        return False


def _fresh_generation() -> int:
    """Epoch stamp distinguishing restarts at the same endpoint
    (SiloAddress.cs generation): full millisecond timestamp in the high bits
    so a later restart ALWAYS gets a higher generation (the membership join
    protocol requires strict monotonicity to declare prior incarnations
    dead); randomized low bits avoid same-millisecond collisions."""
    return (int(time.time() * 1000) << 12) | random.getrandbits(12)


class _Sender:
    """Per-endpoint outbound queue + writer task (the SiloMessageSender
    analog — per-target FIFO, lazy dial, bounded reconnect). Runs on
    whichever loop constructed it: the main loop (classic path), or an
    egress shard's loop (``shard`` set — ``EgressShard._sender``
    constructs it there; encode then uses the per-shard template cache,
    stage timings are STAMPED and replayed loop-side, and outbound
    response envelopes recycle shard-side after their bytes exist)."""

    def __init__(self, fabric: "SocketFabric", endpoint: str, shard=None):
        self.fabric = fabric
        self.endpoint = endpoint
        self.shard = shard      # multiloop.EgressShard | None
        self.queue: asyncio.Queue[Message] = asyncio.Queue()
        self.task = asyncio.get_running_loop().create_task(self._run())
        self.writer: asyncio.StreamWriter | None = None
        # negotiated per-link codec: True only once the acceptor's
        # handshake reply advertises hotwire support
        self.peer_native = False
        self._busy = False      # mid-batch flag (drain_idle)

    # -- main-loop feed surface (classic senders; a shard-owned sender
    # -- is fed by its shard instead) ------------------------------------
    def feed(self, msg: Message) -> None:
        self.queue.put_nowait(msg)

    def feed_group(self, msgs: list) -> None:
        q = self.queue
        for m in msgs:
            q.put_nowait(m)

    async def _connect(self) -> asyncio.StreamWriter:
        host, port = self.endpoint.rsplit(":", 1)

        async def dial() -> asyncio.StreamWriter:
            reader, writer = await asyncio.open_connection(host, int(port))
            writer.write(encode_handshake(
                "silo", self.fabric.local_address()))
            await writer.drain()
            # codec negotiation: the acceptor replies with its own
            # handshake; encode at the peer's level from here on
            try:
                self.peer_native = await _read_peer_codec(reader)
            except OSError:
                writer.close()  # failed negotiation: redial, don't leak
                raise
            return writer

        try:
            # jittered backoff so N senders dialing a restarted silo don't
            # retry in lockstep
            return await retry(
                dial, max_attempts=_CONNECT_RETRIES, retry_on=OSError,
                backoff=ExponentialBackoff(min_delay=_CONNECT_BACKOFF,
                                           max_delay=2.0))
        except OSError as e:
            raise SiloUnavailableError(
                f"cannot connect to {self.endpoint}: {e}") from e

    async def _run(self) -> None:
        # loop attribution: everything this task does — wire encode and
        # the transport write — is outbound work; "egress" is the slice
        # the sharded-egress A/B moves off the main loop (a shard-owned
        # sender books it on the shard loop's own profiler instead)
        from ..observability.profiling import mark_loop_category
        mark_loop_category("egress")
        shard = self.shard
        while True:
            msg = await self.queue.get()
            batch = _drain_batch(self.queue, msg)
            if shard is not None:
                # backpressure accounting (EgressShard.pending, keyed
                # by endpoint): these leave the sender queue NOW — at
                # most one in-flight batch (<= _SEND_BATCH_MAX) goes
                # uncounted while a wedged peer blocks the write below;
                # the queue refilling behind it is what the feed bound
                # reads. Missing key = _close_endpoint already
                # reconciled this sender: no-op, a re-dialed sender's
                # fresh entry must not go negative.
                if self.endpoint in shard.pending:
                    shard.pending[self.endpoint] -= sum(
                        1 for m in batch
                        if m.category is Category.APPLICATION)
            if self.fabric.is_endpoint_dead(self.endpoint):
                # dead-silo drop (MessageCenter SiloDeadOracle): the
                # shard-owned batch's dead RESPONSE shells still go
                # back to the pool — every drop path recycles (the
                # ring-full path does via _egress_dropped)
                if shard is not None:
                    shard._recycle_responses(batch)
                continue
            self._busy = True
            bounced: list = []
            try:
                if self.writer is None or self.writer.is_closing():
                    self.writer = await self._connect()
                # encode AFTER the (re)connect: peer_native is per-link.
                if shard is None:
                    await self._send_batch_loopside(batch)
                else:
                    await self._send_batch_sharded(shard, batch, bounced)
            except (SiloUnavailableError, OSError, FrameError) as e:
                log.warning("send to %s failed: %s", self.endpoint, e)
                if self.writer is not None:
                    self.writer.close()
                    self.writer = None
                # dropped: senders learn via response timeout /
                # membership — the now-dead outbound responses of a
                # shard-owned batch still recycle (finally below)
            finally:
                if shard is not None:
                    # encode-then-recycle, every path: success, encode
                    # bounce, and send failure all end these envelopes'
                    # lifecycles (requests stay out — correlation owns
                    # them sender-side). BOUNCED envelopes stay out
                    # too: their bounce is marshalled to the main loop
                    # and still in flight — recycling here would let
                    # the pool re-issue the shell before the callback
                    # reads it (identity filter: Message.__eq__ is
                    # field-comparing).
                    if bounced:
                        skip = set(map(id, bounced))
                        shard._recycle_responses(
                            [m for m in batch if id(m) not in skip])
                    else:
                        shard._recycle_responses(batch)
                self._busy = False

    async def _send_batch_loopside(self, batch: list) -> None:
        """The classic main-loop drain: encode against the shared
        template cache, stats straight into the registry (we ARE the
        loop), one vectored write."""
        # egress.encode is the RESPONSE-path stage: only batches
        # carrying responses observe it (a pure request drain booking
        # into it would inflate the response-path share the attribution
        # harness reports; responses co-batched with requests share one
        # write, so the whole encode is honestly theirs-or-shared)
        est = self.fabric.egress_stats
        if est is not None and not any(
                m.direction == Direction.RESPONSE for m in batch):
            est = None
        chunks = encode_message_batch(
            batch, self.fabric.bounce_unencodable,
            native=self.peer_native, stats=est,
            templates=self.fabric.response_templates)
        if not chunks:
            return
        led = self.fabric.ledger
        if led is not None:
            # main-loop sender: the ledger is loop-confined here, charge
            # directly (the sharded path stamps instead)
            led.charge_wire(f"peer:{self.endpoint}",
                            tx=sum(len(c) for c in chunks))
        _writev_stream(self.writer, chunks)
        await self.writer.drain()

    async def _send_batch_sharded(self, shard, batch: list,
                                  bounced: list) -> None:
        """The shard-loop drain: per-shard template cache, encode bounce
        MARSHALLED to the main loop (``bounce_unencodable`` routes
        through main-loop state; the bounced envelope joins ``bounced``
        so the caller's recycle sweep leaves it for the in-flight
        callback to own), dwell/encode STAMPED here and replayed
        loop-side over the shard's stat ring — the registries are
        loop-confined, so no live registry ever crosses into this
        context (the OTPU007 contract)."""
        fab = self.fabric
        main = shard.main_loop

        def _bounce(m, e):
            bounced.append(m)
            try:
                main.call_soon_threadsafe(fab.bounce_unencodable, m, e)
            except RuntimeError:
                # main loop gone (process teardown): the bounce is
                # moot, but raising here would escape _run's except
                # tuple and kill the sender task
                pass

        stamps = shard._dwell_stamps(batch)
        t0 = time.monotonic()
        chunks = encode_message_batch(
            batch, _bounce,
            native=self.peer_native, stats=None,
            templates=fab.response_templates,
            tmpl_cache=shard.tmpl_cache)
        if chunks and stamps is not None and any(
                m.direction == Direction.RESPONSE for m in batch):
            stamps.append((_EG_ENCODE, time.monotonic() - t0))
        if chunks and stamps is not None and fab.ledger is not None:
            # wire-byte charge stamped for loop-side replay (the shard
            # may not touch the loop-confined ledger)
            stamps.append((_LEDGER_WIRE,
                           (f"peer:{self.endpoint}",
                            sum(len(c) for c in chunks))))
        if stamps:
            shard.stat_ring.push((0, stamps), 0)
        if not chunks:
            return
        shard.encoded += 1
        _writev_stream(self.writer, chunks)
        await self.writer.drain()

    async def drain_idle(self, timeout: float) -> None:
        """Best-effort queue flush (clean-shutdown drain): wait until
        the queue is empty and the writer task is parked back on
        ``queue.get`` — bounded, a dead peer's reconnect backoff must
        not hold shutdown hostage."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while (self.queue.qsize() or self._busy) and \
                loop.time() < deadline:
            await asyncio.sleep(0.01)

    def close(self) -> None:
        self.task.cancel()
        if self.writer is not None:
            self.writer.close()
            self.writer = None


class _ShardSenderHandle:
    """Main-loop face of a shard-owned silo-peer sender (sharded
    egress): application traffic — flush groups and per-message sends
    alike — crosses the shard's SPSC egress ring (ring FIFO keeps
    per-message sends ordered behind the groups ``flush_dest`` drained
    first), while PING/SYSTEM bypasses the ring per-message so a probe
    response can never sit behind ring backpressure (the QoS split).
    The actual :class:`_Sender` (queue + dial + encode + writev) lives
    on the shard loop — ``EgressShard._sender`` constructs it there."""

    __slots__ = ("fabric", "shard", "endpoint")

    def __init__(self, fabric: "SocketFabric", shard, endpoint: str):
        self.fabric = fabric
        self.shard = shard
        self.endpoint = endpoint

    def feed(self, msg: Message) -> None:
        shard = self.shard
        if shard.pool.closed:
            self.fabric._classic_sender(self.endpoint).feed(msg)
            return
        # clear the local arrival stamp before the hand-off: on a
        # relayed envelope it is INGRESS time — shard-side dwell must
        # only ever see the egress accumulator's send-side stamps
        # (feed_group), and the slot is wire-excluded dead weight here
        msg.received_at = None
        if msg.category is not Category.APPLICATION:
            shard.peer_direct(self.endpoint, msg)
        elif not shard.feed_peer(self.endpoint, msg, 1):
            self.fabric._egress_dropped(shard, [msg])

    def feed_group(self, msgs: list) -> None:
        shard = self.shard
        if shard.pool.closed:
            self.fabric._classic_sender(self.endpoint).feed_group(msgs)
            return
        if not shard.feed_peer(self.endpoint, msgs, len(msgs)):
            self.fabric._egress_dropped(shard, msgs)

    def close(self) -> None:
        try:
            self.shard.loop.call_soon_threadsafe(
                self.shard._close_endpoint, self.endpoint)
        except RuntimeError:
            pass  # shard loop gone: its senders died with it


class _PoolAcceptor:
    """Server-shaped handle for a multi-loop silo's acceptor (what
    ``unregister_silo`` closes in place of the asyncio server)."""

    __slots__ = ("pool",)

    def __init__(self, pool):
        self.pool = pool

    def close(self) -> None:
        self.pool.close_acceptor()


class SocketFabric:
    """Drop-in fabric (same surface the Silo/clients use as InProcFabric)
    whose wire is real TCP. One instance per process; it may host several
    silos (each with its own listening socket) for tests."""

    def __init__(self, host: str = "127.0.0.1"):
        self.host = host
        self.silos: dict[SiloAddress, Any] = {}      # local silos only
        self.dead: set[SiloAddress] = set()
        self._dead_endpoints: set[str] = set()
        self._listen_socks: dict[str, socket.socket] = {}  # name -> bound sock
        self._servers: dict[SiloAddress, asyncio.base_events.Server] = {}
        self._senders: dict[str, _Sender] = {}
        # client pseudo-address -> writer for clients connected to our gateway
        self.client_routes: dict[SiloAddress, asyncio.StreamWriter] = {}
        # negotiated codec per client route (handshake-advertised)
        self._client_native: dict[SiloAddress, bool] = {}
        # which local silo's gateway each client route belongs to
        self._route_owner: dict[SiloAddress, SiloAddress] = {}
        self._conn_tasks: set[asyncio.Task] = set()
        self.partitions: set[tuple[str, str]] = set()
        self._names = itertools.count(1)
        # egress stage metrics (EGRESS_STATS): the registry of the first
        # metrics-enabled local silo, else None — the sender/client-route
        # encode paths pay one attribute load (senders are shared per
        # endpoint, so per-silo attribution is not available here)
        self.egress_stats = None
        # cost-attribution ledger of the first ledger-enabled local silo
        # (same sharing rule as egress_stats): senders/client routes
        # charge wire bytes per route through it
        self.ledger = None
        # header-prefix wire templates for response batches
        # (wire.encode_message_batch templates= switch): cleared when any
        # local silo runs batched_egress=False so the A/B lever also
        # restores the per-frame header encode (bytes are identical
        # either way — this only flips WHICH encoder produced them)
        self.response_templates = True
        # sharded egress (runtime.multiloop.EgressShardPool): constructed
        # by register_silo when a local silo has egress_shards >= 1;
        # None = every sender/encode/write stays on the main loop
        self.egress_pool = None
        # peer endpoint -> ingress shard index owning the INBOUND half of
        # that peering (recorded at the shard handshake, marshalled here:
        # main-loop state) — the egress pool's link-affinity source
        self._peer_shard: dict[str, int] = {}
        # main-loop occupancy profiler (set by the silo when profiling is
        # on): the inline client-route encode+write paths book their
        # slice under "egress" so the sharded-egress A/B is measurable
        self.loop_prof = None
        # multi-process silo (runtime.multiproc) relay state. All three
        # stay empty/None under worker_procs=1 — the delivery hot path
        # pays one falsy check on its MISS branches only.
        #   route_relays: owner-side, client pseudo-address -> internal
        #     endpoint of the worker holding that connection (announced
        #     over the staging rings); consulted after a client_routes
        #     miss because the pseudo-address carries the ADVERTISED
        #     endpoint — dialing it would let the kernel hand the
        #     connection to an arbitrary reuseport worker
        self.route_relays: dict[SiloAddress, str] = {}
        #   endpoint_aliases: worker-side, advertised endpoint -> the
        #     owner's internal endpoint; a message for a client another
        #     process holds routes to the owner, which relays
        self.endpoint_aliases: dict[str, str] = {}
        #   route_notify: worker-side callback (addr, up) fired when a
        #     client route registers/drops, so the owner's relay table
        #     tracks this process's connections
        self.route_notify = None
        #   gateway_drop_endpoint: owner-side, the advertised endpoint —
        #     a client target there with NO relay is dropped, never
        #     dialed (the kernel would hand the new connection to an
        #     arbitrary reuseport worker, not the client)
        self.gateway_drop_endpoint: str | None = None

    # -- address allocation ---------------------------------------------
    def allocate_address(self, name: str,
                         reuseport: bool = False) -> SiloAddress:
        """Bind + listen immediately so peers can connect (backlog) even
        before the asyncio server attaches in register_silo — no startup
        race between silos dialing each other. ``reuseport=True``
        reserves a multi-process ADVERTISED endpoint: the socket opens
        an SO_REUSEPORT accept group that forked worker processes join
        with their own listeners (the owner's copy never accepts and
        closes once the workers are serving)."""
        if reuseport:
            from .multiproc import _reuseport_listener
            sock = _reuseport_listener(self.host, 0)
        else:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((self.host, 0))
            sock.listen(128)
            sock.setblocking(False)
        port = sock.getsockname()[1]
        addr = SiloAddress(self.host, port, _fresh_generation())
        self._listen_socks[addr.endpoint] = sock
        return addr

    def local_address(self) -> SiloAddress:
        if not self.silos:
            raise SiloUnavailableError("no local silo registered")
        return next(iter(self.silos))

    # -- silo lifecycle ----------------------------------------------------
    def register_silo(self, silo: "Silo") -> None:
        addr = silo.silo_address
        self.silos[addr] = silo
        self.dead.discard(addr)
        if self.egress_stats is None and silo.ingest_stats is not None:
            self.egress_stats = silo.stats
        if self.ledger is None and silo.ledger is not None:
            self.ledger = silo.ledger
        if not silo.config.batched_egress:
            self.response_templates = False
        sock = self._listen_socks.get(addr.endpoint)
        if sock is None:
            raise SiloUnavailableError(
                f"silo address {addr} was not allocated by this fabric")
        if silo.config.ingress_loops > 1 and silo.ingress_pool is None:
            # multi-loop silo (runtime.multiloop): N ingress pump
            # threads, each with its own event loop + vectored socket
            # pump, fed by the round-robin acceptor below over SPSC
            # hand-off rings. ingress_loops=1 (default) constructs none
            # of this — the start_server path below is today's bit for
            # bit.
            from .multiloop import IngressLoopPool
            silo.ingress_pool = IngressLoopPool(
                silo, silo.config.ingress_loops)
            silo.ingress_pool.start()
        if silo.config.egress_shards > 0 and self.egress_pool is None:
            # sharded egress (runtime.multiloop): silo-peer senders and
            # shard-owned client-route writes move onto shard loops, fed
            # over SPSC egress rings from this loop. Borrows the ingress
            # shards when the silo runs multi-loop (link-ownership
            # affinity), else spawns dedicated egress loop threads.
            # egress_shards=0 (default) constructs none of this.
            from .multiloop import EgressShardPool
            self.egress_pool = EgressShardPool(
                self, silo, silo.config.egress_shards,
                ingress_pool=silo.ingress_pool)
        loop = asyncio.get_running_loop()
        t = loop.create_task(self._serve(silo, sock))
        self._conn_tasks.add(t)
        t.add_done_callback(self._conn_tasks.discard)
        if silo.membership is not None:
            silo.membership.subscribe(self._on_membership_change)

    async def _serve(self, silo: "Silo", sock: socket.socket) -> None:
        pool = silo.ingress_pool
        if pool is None:
            server = await asyncio.start_server(
                lambda r, w: self._handle_conn(silo, r, w), sock=sock)
            self._servers[silo.silo_address] = server
            return
        # multi-loop acceptor: the listener runs on the main loop and
        # hands each accepted socket round-robin to an ingress shard
        # (the listener-thread form of the reference's acceptor; one
        # process needs no SO_REUSEPORT for this). The shard owns the
        # connection — handshake, pump, and client-route writes all run
        # on its loop.
        accept_task = asyncio.current_task()

        def _close() -> None:
            if accept_task is not None:
                accept_task.cancel()
            sock.close()

        pool.accept_handle = _close
        self._servers[silo.silo_address] = _PoolAcceptor(pool)
        loop = asyncio.get_running_loop()
        try:
            while not pool.closed:
                conn, _peer = await loop.sock_accept(sock)
                conn.setblocking(False)
                pool.assign().submit_conn(self, silo, conn)
        except asyncio.CancelledError:
            pass
        except OSError:
            pass  # listener closed under us (silo stopping)

    def unregister_silo(self, silo: "Silo", dead: bool = False) -> None:
        addr = silo.silo_address
        self.silos.pop(addr, None)
        if dead:
            self.dead.add(addr)
        server = self._servers.pop(addr, None)
        if server is not None:
            server.close()
        self._listen_socks.pop(addr.endpoint, None)
        # close only the routes of clients attached to THIS silo's gateway
        for caddr, owner in list(self._route_owner.items()):
            if owner == addr:
                self._route_owner.pop(caddr, None)
                self._client_native.pop(caddr, None)
                w = self.client_routes.pop(caddr, None)
                if w is not None:
                    w.close()
        # shared outbound senders survive while other local silos need them
        if not self.silos:
            for s in list(self._senders.values()):
                s.close()
            self._senders.clear()
            for w in self.client_routes.values():
                w.close()
            self.client_routes.clear()
            self._route_owner.clear()
            for t in list(self._conn_tasks):
                t.cancel()

    # -- membership-driven liveness ---------------------------------------
    def _on_membership_change(self, alive: list[SiloAddress],
                              dead: list[SiloAddress]) -> None:
        for d in dead:
            self.dead.add(d)
            self._dead_endpoints.add(d.endpoint)
            sender = self._senders.pop(d.endpoint, None)
            if sender is not None:
                sender.close()
        # a restarted silo reuses an endpoint with a new generation
        for a in alive:
            self._dead_endpoints.discard(a.endpoint)

    def is_dead(self, addr: SiloAddress) -> bool:
        return addr in self.dead

    def is_endpoint_dead(self, endpoint: str) -> bool:
        return endpoint in self._dead_endpoints

    def alive_silos(self) -> list[SiloAddress]:
        """Cluster view: from the membership oracle when running, else the
        local silos (bootstrap)."""
        for silo in self.silos.values():
            if silo.membership is not None:
                return silo.membership.active_silos()
        return [a for a, s in self.silos.items()
                if s.status in ("Running", "Joining")]

    # -- fault injection (parity with InProcFabric) ------------------------
    def partition(self, a: SiloAddress, b: SiloAddress) -> None:
        self.partitions.add((a.endpoint, b.endpoint))
        self.partitions.add((b.endpoint, a.endpoint))

    def heal_partition(self, a: SiloAddress, b: SiloAddress) -> None:
        self.partitions.discard((a.endpoint, b.endpoint))
        self.partitions.discard((b.endpoint, a.endpoint))

    # -- the wire ----------------------------------------------------------
    def deliver(self, msg: Message) -> None:
        target = msg.target_silo
        if target is None:
            log.warning("dropping unaddressed message %s", msg.method_name)
            return
        if msg.sending_silo is not None and \
                (msg.sending_silo.endpoint, target.endpoint) in self.partitions:
            return
        local = self.silos.get(target)
        if local is not None:
            local.message_center.deliver(msg)
            return
        client_writer = self.client_routes.get(target)
        if client_writer is not None:
            self._write_to_client(target, client_writer, msg)
            return
        if self.route_relays or self.endpoint_aliases or \
                self.gateway_drop_endpoint is not None:
            ep = self._relay_endpoint(target, msg)
            if ep is not _NO_RELAY:
                if ep is not None:
                    self._sender_for(ep).feed(msg)
                return
        if target in self.dead:
            return
        self._sender_for(target.endpoint).feed(msg)

    def _relay_endpoint(self, target: SiloAddress, msg: Message):
        """Multi-process relay resolution for a client pseudo-address
        another process holds (runtime.multiproc). Returns the internal
        endpoint to relay through, None when the message was consumed
        (dropped: unroutable or over the hop bound), or ``_NO_RELAY``
        when this target is not a relay case at all. The forward count
        bounds the worker->owner->worker path exactly like dispatcher
        forwards — a stale relay can bounce at most that many times."""
        ep = self.route_relays.get(target) if self.route_relays else None
        if ep is None and self.endpoint_aliases:
            ep = self.endpoint_aliases.get(target.endpoint)
        if ep is None:
            if target.endpoint == self.gateway_drop_endpoint:
                log.info("dropping message for client %s with no relay "
                         "route (disconnected)", target)
                return None
            return _NO_RELAY
        from .dispatcher import MAX_FORWARD_COUNT
        if msg.forward_count >= MAX_FORWARD_COUNT:
            log.info("dropping message for unroutable client %s "
                     "(relay hop bound)", target)
            return None
        msg.forward_count += 1
        return ep

    # -- outbound sender placement (sharded egress) -----------------------
    def _sender_for(self, endpoint: str):
        """The outbound sender (or shard handle) for one endpoint. With
        an egress pool, new links go to the shard that owns the inbound
        half of the peering (round-robin when connect-side only) and the
        main loop keeps only the ring feed; without one, the classic
        main-loop ``_Sender``."""
        sender = self._senders.get(endpoint)
        if sender is None:
            pool = self.egress_pool
            if pool is not None and not pool.closed:
                sender = _ShardSenderHandle(
                    self, pool.shard_for(endpoint), endpoint)
            else:
                sender = _Sender(self, endpoint)
            self._senders[endpoint] = sender
        return sender

    def _classic_sender(self, endpoint: str) -> _Sender:
        """Force a main-loop ``_Sender`` for one endpoint (egress-pool
        teardown: shard handles detach and late sends fall back here)."""
        s = self._senders.get(endpoint)
        if not isinstance(s, _Sender):
            s = self._senders[endpoint] = _Sender(self, endpoint)
        return s

    def _detach_shard_senders(self) -> None:
        """Egress-pool close: drop the shard handles so later sends
        build classic senders (the shards flush what they already
        hold — the clean-shutdown drain)."""
        for ep, s in list(self._senders.items()):
            if isinstance(s, _ShardSenderHandle):
                del self._senders[ep]

    def _record_peer_shard(self, endpoint: str, index: int) -> None:
        self._peer_shard[endpoint] = index

    def _forget_peer_shard(self, endpoint: str, index: int) -> None:
        if self._peer_shard.get(endpoint) == index:
            self._peer_shard.pop(endpoint, None)

    def _egress_dropped(self, shard, msgs: list) -> None:
        """Bounded backpressure hit: an egress ring past capacity
        dropped application traffic toward a slow/wedged consumer.
        Count it, say so once per shard, and recycle the now-dead
        response envelopes (senders learn via response timeout — the
        dead-peer drop semantics)."""
        est = self.egress_stats
        if est is not None:
            est.increment(_EG_RING_DROPS, len(msgs))
        if shard.drops == len(msgs):  # first drop on this shard
            log.warning("egress ring full (shard %d): dropping "
                        "application messages toward a slow consumer",
                        shard.index)
        dead = [m for m in msgs if m.direction == Direction.RESPONSE]
        if dead:
            recycle_messages(dead)

    def sharded_dest(self, dest) -> bool:
        """True when responses to ``dest`` will encode shard-side (the
        egress batcher then leaves its dwell stamps for the shard to
        observe — dwell spans accumulator + ring + sender queue).
        Derived from the sender/route actually INSTALLED, not from
        topology: a classic main-loop sender cached from before the
        pool existed keeps observing dwell loop-side."""
        pool = self.egress_pool
        if pool is None or pool.closed or dest is None:
            return False
        if dest in self.silos:
            return False  # in-proc loopback: never leaves the loop
        w = self.client_routes.get(dest)
        if w is not None:
            return getattr(w, "egress_shard", None) is not None
        if dest in self.dead:
            return False  # send_batch drops these before any sender
        s = self._senders.get(dest.endpoint)
        if s is not None:
            return isinstance(s, _ShardSenderHandle)
        return True  # no sender yet: _sender_for builds a shard handle

    def _client_encode_error(self, addr: SiloAddress,
                             writer: asyncio.StreamWriter, msg: Message,
                             e: Exception, native: bool) -> None:
        """A message to a gateway client failed to *encode*: the route is
        healthy, only this payload is bad. Fail the call promptly with a
        portable error response instead of letting the client time out.
        Shared by the per-message and batched client write paths."""
        log.warning("unencodable message to client %s: %s", addr, e)
        if msg.direction == Direction.RESPONSE:
            from ..core.message import ResponseKind
            fallback = Message.__new__(Message)
            for s in Message.__slots__:
                setattr(fallback, s, getattr(msg, s))
            fallback.response_kind = ResponseKind.ERROR
            fallback.body = SiloUnavailableError(
                f"response to {msg.interface_name}.{msg.method_name} "
                f"could not cross the wire: {e}")
            try:
                writer.write(encode_message(fallback, native=native))
            except Exception:  # noqa: BLE001
                log.exception("error-response fallback failed")

    def _drop_client_route(self, addr: SiloAddress) -> None:
        self.client_routes.pop(addr, None)
        self._route_owner.pop(addr, None)
        self._client_native.pop(addr, None)
        if self.route_notify is not None:
            self.route_notify(addr, False)

    def _stream_write_client(self, addr: SiloAddress, writer,
                             data: bytes) -> None:
        """Main-loop tail of a shard-encoded client write (standalone
        egress over a plain StreamWriter): the shard already paid the
        encode; only the fd write lands here."""
        try:
            writer.write(data)
        except Exception:  # noqa: BLE001 — client gone mid-write
            log.info("dropping message to disconnected client %s", addr)
            if self.client_routes.get(addr) is writer:
                self._drop_client_route(addr)

    @staticmethod
    def _marshal_client_write(writer, data: bytes) -> None:
        """Egress-pool-teardown fallback for a shard-bound route: the
        writer's ops are loop-bound, so bytes encoded here marshal to
        its loop (a dead shard loop means the route is dying anyway)."""
        try:
            writer._loop.call_soon_threadsafe(writer.write, data)
        except RuntimeError:
            pass

    def _write_to_client(self, addr: SiloAddress,
                         writer: asyncio.StreamWriter, msg: Message) -> None:
        es = getattr(writer, "egress_shard", None)
        native = self._client_native.get(addr, False)
        if es is not None:
            # shard-owned route: encode + write happen on the shard.
            # Clear the local arrival stamp first — on a forwarded
            # envelope it is INGRESS time, not egress dwell (see
            # _ShardSenderHandle.feed)
            msg.received_at = None
            if not es.pool.closed:
                if msg.category is not Category.APPLICATION:
                    es.client_direct(addr, writer, native, msg)
                else:
                    es.feed_client(addr, writer, native, [msg])
                return
            try:  # pool torn down, route still shard-bound: marshal
                data = encode_message(msg, native=native)
            except Exception as e:  # noqa: BLE001
                log.warning("unencodable message to client %s during "
                            "egress teardown: %s", addr, e)
                return
            self._marshal_client_write(writer, data)
            return
        lp = self.loop_prof
        tok = lp.enter("egress") if lp is not None else None
        try:
            try:
                data = encode_message(msg, native=native)
            except Exception as e:  # noqa: BLE001 — per-payload, not the route
                self._client_encode_error(addr, writer, msg, e, native)
                return
            if self.ledger is not None:
                # main-loop gateway write (per-message path): charge the
                # client route directly (we ARE the loop)
                self.ledger.charge_wire(f"client:{addr}", tx=len(data))
            try:
                writer.write(data)
            except Exception:  # noqa: BLE001 — client gone mid-write
                log.info("dropping message to disconnected client %s", addr)
                self._drop_client_route(addr)
        finally:
            if tok is not None:
                lp.exit(tok)

    def _write_client_batch(self, addr: SiloAddress,
                            writer: asyncio.StreamWriter,
                            msgs: list) -> None:
        """Batched gateway→client write: ONE ``encode_message_batch``
        (header-prefix template on the native path) + one transport write
        for a whole response group — the per-message path encoded and
        wrote each response alone, the exact N-hops-per-inbound-batch
        residue batched egress removes. Encode failures scope to one
        message via the shared error-response fallback. Sharded egress:
        a shard-owned route takes the whole Message list across the
        shard's egress ring instead — encode (per-shard template
        cache) + writev + the response recycle sweep all run on the
        shard loop, and only the ring push stays here."""
        native = self._client_native.get(addr, False)
        es = getattr(writer, "egress_shard", None)
        if es is not None:
            if not es.pool.closed:
                es.feed_client(addr, writer, native, msgs)
                return
            chunks = encode_message_batch(  # teardown fallback: marshal
                msgs, lambda m, e: log.warning(
                    "unencodable message to client %s during egress "
                    "teardown: %s", addr, e),
                native=native, templates=self.response_templates)
            if chunks:
                self._marshal_client_write(writer, b"".join(chunks))
            return
        lp = self.loop_prof
        tok = lp.enter("egress") if lp is not None else None
        try:
            chunks = encode_message_batch(
                msgs,
                lambda m, e: self._client_encode_error(addr, writer, m, e,
                                                       native),
                native=native, stats=self.egress_stats,
                templates=self.response_templates)
            if not chunks:
                return
            if self.ledger is not None:
                # main-loop gateway write: charge the client route
                # directly (we ARE the loop)
                self.ledger.charge_wire(f"client:{addr}",
                                        tx=sum(len(c) for c in chunks))
            try:
                # shard-owned routes (multiloop.ShardWriter) take the
                # chunk list whole — it rides one writev, no join copy
                write_many = getattr(writer, "write_many", None)
                if write_many is not None:
                    write_many(chunks)
                else:
                    writer.write(b"".join(chunks))
            except Exception:  # noqa: BLE001 — client gone mid-write
                log.info("dropping batch to disconnected client %s", addr)
                self._drop_client_route(addr)
        finally:
            if tok is not None:
                lp.exit(tok)

    def deliver_group(self, target: SiloAddress, msgs: list) -> None:
        """Batched outbound hand-off for ONE destination
        (``MessageCenter.send_batch``): a local silo gets one
        ``deliver_batch``, a gateway client route one batched encode +
        write, and a remote silo one sender-queue fill (the writer task
        wakes once and drains the whole group as a single wire batch —
        deliberate fill, not greedy-drain luck)."""
        if target is None:
            log.warning("dropping %d unaddressed batched messages",
                        len(msgs))
            return
        first = msgs[0]
        if first.sending_silo is not None and \
                (first.sending_silo.endpoint,
                 target.endpoint) in self.partitions:
            return  # one sender, one target: the whole group is cut
        local = self.silos.get(target)
        if local is not None:
            local.message_center.deliver_batch(msgs)
            return
        client_writer = self.client_routes.get(target)
        if client_writer is not None:
            self._write_client_batch(target, client_writer, msgs)
            return
        if self.route_relays or self.endpoint_aliases or \
                self.gateway_drop_endpoint is not None:
            ep = self._relay_endpoint(target, first)
            if ep is not _NO_RELAY:
                if ep is not None:
                    for m in msgs[1:]:
                        m.forward_count += 1
                    self._sender_for(ep).feed_group(msgs)
                return
        if target in self.dead:
            return
        self._sender_for(target.endpoint).feed_group(msgs)

    # -- inbound connections ----------------------------------------------
    async def _handle_conn(self, silo: "Silo", reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        peer_addr: SiloAddress | None = None
        is_client = False
        try:
            headers, _ = await read_frame(reader)
            hs = decode_handshake(headers)
            peer_addr = hs["address"]
            is_client = hs["kind"] == "client"
            # codec negotiation: reply with OUR handshake so the dialer
            # learns whether this process can decode hotwire frames; from
            # here on each side encodes at the peer's advertised level
            writer.write(encode_handshake("silo", silo.silo_address))
            await writer.drain()
            if is_client:
                # Gateway: record the client route (ClientObserverRegistrar
                # records gateway routes; here route == live connection)
                self.client_routes[peer_addr] = writer
                self._route_owner[peer_addr] = silo.silo_address
                self._client_native[peer_addr] = bool(
                    hs.get("hotwire", False))
                pool = self.egress_pool
                if pool is not None and not pool.closed and \
                        silo.ingress_pool is None:
                    # standalone-egress residue fix: pin this client
                    # route to an egress shard so its response encodes
                    # leave the main loop like silo-peer links already
                    # do (multi-loop ingress pins routes shard-side)
                    writer.egress_shard = pool.shard_for_client(peer_addr)
                if self.route_notify is not None:
                    # multi-process worker: announce the route so the
                    # owner can relay responses produced elsewhere
                    self.route_notify(peer_addr, True)
            # ingest stage metrics (observability.stats.INGEST_STATS):
            # decode is timed inside decode_frames/decode_message (which
            # also stamp the envelope's received_at) and frames-per-read
            # lands in the batch histogram. The later stages (enqueue/
            # queue_wait) are observed downstream where the envelope is
            # provably still live — routing can consume a message
            # synchronously (inline turns, response correlation +
            # recycle), so NOTHING here may touch msg after routing.
            ist = silo.ingest_stats
            if silo.loop_prof is not None:
                # loop-occupancy attribution: this handler task's steps —
                # socket reads, wire decode, batched routing (including
                # inline turns' first synchronous stretch until the turn
                # re-labels itself) — are pump work on the loop
                from ..observability.profiling import mark_loop_category
                mark_loop_category("pump")
            if silo.config.batched_ingress:
                await self._pump_batched(silo, reader, ist,
                                         route=f"in:{peer_addr}")
            else:
                # per-frame hand-off (the batched-ingress A/B lever):
                # decode + route one message per frame
                on_batch = None
                if ist is not None:
                    from ..observability.stats import (COUNT_BOUNDS,
                                                       INGEST_STATS)
                    on_batch = ist.histogram_with(
                        INGEST_STATS["frame_batch"], COUNT_BOUNDS).observe
                async for headers, body in frame_stream(reader,
                                                        on_batch=on_batch):
                    try:
                        msg = decode_message(headers, body, ist)
                    except _BodyDecodeError as e:
                        self._bounce_undecodable(e.message, str(e))
                        continue
                    except WireDecodeError as e:
                        # headers undecodable: scoped to this message —
                        # the frame was fully consumed, the link is fine
                        log.warning("dropping message with undecodable "
                                    "headers: %s", e)
                        continue
                    self._route_inbound(silo, msg)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass  # clean EOF / peer died
        except FrameError as e:
            log.warning("dropping connection from %s: %s", peer_addr, e)
        except Exception:  # noqa: BLE001
            log.exception("connection handler failed (peer=%s)", peer_addr)
        finally:
            # a reconnected client may have re-handshaked on a NEW connection
            # that overwrote this route — only remove the route if it is
            # still ours
            if is_client and peer_addr is not None and \
                    self.client_routes.get(peer_addr) is writer:
                self.client_routes.pop(peer_addr, None)
                self._route_owner.pop(peer_addr, None)
                self._client_native.pop(peer_addr, None)
                if self.route_notify is not None:
                    self.route_notify(peer_addr, False)
            writer.close()

    async def _pump_batched(self, silo: "Silo",
                            reader: asyncio.StreamReader, ist,
                            route: str = "") -> None:
        """Batched receive pump: every complete frame buffered after one
        socket read decodes in ONE ``decode_frames`` pass (a single
        ``unpack_batch`` C call on the native build) and the decoded list
        rides one batched hand-off into the message center — the
        receive-side symmetric of the sender's greedy ``_drain_batch``.
        This pump runs ON the silo's loop, so the cost ledger (when
        enabled) is passed live into the reader for per-route rx
        charges."""
        async for msgs, bounces in _read_frame_batches(reader, ist,
                                                       silo.ledger, route,
                                                       strict_tail=True):
            for e in bounces:
                self._bounce_undecodable(e.message, str(e))
            if msgs:
                self._route_inbound_batch(silo, msgs)

    def _route_inbound_batch(self, silo: "Silo", msgs: list) -> None:
        """Batched ``_route_inbound``: messages for a local silo ride ONE
        ``MessageCenter.deliver_batch`` hand-off per destination (the
        queue-wait killer); gateway-forwarded client deliveries and
        relays peel off to the per-message path. Grouping preserves
        arrival order per destination, which is all the wire ever
        guaranteed (per-sender FIFO per target)."""
        groups: dict[Any, list] = {}
        for msg in msgs:
            target = msg.target_silo
            if target is None:
                local = silo
            else:
                local = self.silos.get(target)
            if local is not None:
                g = groups.get(local.message_center)
                if g is None:
                    g = groups[local.message_center] = []
                g.append(msg)
            else:
                # client route / stale target / relay: per-message path
                self._route_inbound(silo, msg)
        for center, batch in groups.items():
            center.deliver_batch(batch)

    def _route_inbound(self, silo: "Silo", msg: Message) -> None:
        target = msg.target_silo
        if target is not None:
            local = self.silos.get(target)
            if local is not None:
                local.message_center.deliver(msg)
                return
            client_writer = self.client_routes.get(target)
            if client_writer is not None:
                # gateway forwarding to a connected client
                # (Gateway.TryDeliverToProxy:229)
                self._write_to_client(target, client_writer, msg)
                return
            if target.same_endpoint(silo.silo_address):
                # addressed to a client of ours that disconnected, or to an
                # older generation of this silo: drop (sender times out /
                # re-addresses via directory)
                log.info("dropping message for unknown local target %s",
                         target)
                return
            # misrouted: relay toward the addressed silo
            self.deliver(msg)
            return
        # unaddressed (client gateway ingress): this silo addresses it
        silo.message_center.deliver(msg)

    def bounce_unencodable(self, msg: Message, exc: Exception) -> None:
        """A message failed to *encode* (unpicklable payload). Requests get
        an error response back to the caller; anything else is dropped."""
        if msg.direction == Direction.RESPONSE or msg.sending_silo is None:
            log.warning("dropping unencodable %s: %s", msg.method_name, exc)
            return
        from ..core.message import make_error_response
        self.deliver(make_error_response(msg, SiloUnavailableError(
            f"wire encode failed for {msg.interface_name}.{msg.method_name}: "
            f"{exc}")))

    def _bounce_undecodable(self, msg: Message, info: str) -> None:
        """Body failed to decode; headers survived, so reject back to the
        sender instead of letting the call time out."""
        if msg.direction == Direction.RESPONSE or msg.sending_silo is None:
            log.warning("dropping undecodable %s: %s", msg.method_name, info)
            return
        from ..core.message import RejectionType, make_rejection
        rej = make_rejection(msg, RejectionType.UNRECOVERABLE,
                             f"wire decode failed: {info}")
        self.deliver(rej)

    # -- in-proc client compatibility --------------------------------------
    def register_client(self, client) -> None:  # pragma: no cover
        raise NotImplementedError(
            "SocketFabric clients connect via GatewayClient, not in-proc")

    def deliver_via_gateway(self, gateway: SiloAddress,
                            msg: Message) -> None:  # pragma: no cover
        raise NotImplementedError(
            "SocketFabric clients connect via GatewayClient, not in-proc")


# ---------------------------------------------------------------------------
# Out-of-process client
# ---------------------------------------------------------------------------

class _GatewayConnection:
    """One TCP connection to one gateway silo (GatewayConnection.cs)."""

    def __init__(self, client: "GatewayClient", endpoint: str):
        self.client = client
        self.endpoint = endpoint
        host, port = endpoint.rsplit(":", 1)
        self.pseudo_address = SiloAddress(host, int(port), client.generation)
        self.writer: asyncio.StreamWriter | None = None
        self.reader_task: asyncio.Task | None = None
        self.queue: asyncio.Queue[Message] = asyncio.Queue()
        self.sender_task: asyncio.Task | None = None
        self.live = False
        self.peer_native = False  # negotiated from the gateway's reply

    async def connect(self) -> None:
        host, port = self.endpoint.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(host, int(port))
        writer.write(encode_handshake("client", self.pseudo_address))
        await writer.drain()
        # codec negotiation: the gateway replies with its own handshake
        try:
            self.peer_native = await _read_peer_codec(reader)
        except OSError:
            writer.close()  # misaligned reply stream must not feed _pump
            raise
        self.writer = writer
        self.live = True
        loop = asyncio.get_running_loop()
        self.reader_task = loop.create_task(self._pump(reader))
        self.sender_task = loop.create_task(self._send_loop())

    async def _pump(self, reader: asyncio.StreamReader) -> None:
        """Client message pump (OutsideRuntimeClient.RunClientMessagePump:235).
        Batched like the silo side: one ``decode_frames`` pass per socket
        read (header-undecodable frames are dropped with a log inside)."""
        # loop-occupancy attribution: this task's steps — response decode
        # + receive_response correlation — are CLIENT gateway machinery,
        # a first-class category so co-hosted harness cost never hides in
        # "other" (one contextvar set; free without a profiler installed)
        from ..observability.profiling import mark_loop_category
        mark_loop_category("client")
        try:
            async for msgs, bounces in _read_frame_batches(
                    reader, strict_tail=False):
                for e in bounces:
                    # a response we cannot decode still completes the call
                    msg = e.message
                    from ..core.message import ResponseKind
                    if msg.direction == Direction.RESPONSE:
                        msg.response_kind = ResponseKind.ERROR
                        msg.body = SiloUnavailableError(
                            f"undecodable response: {e}")
                        self.client.deliver(msg)
                if msgs:
                    # batched correlation: contiguous response runs out of
                    # one socket read resolve in a single
                    # receive_response_batch pass (one freelist sweep)
                    self.client.deliver_batch(msgs)
        except (ConnectionResetError, OSError):
            pass
        except FrameError as e:
            log.warning("gateway %s stream misaligned: %s", self.endpoint, e)
        finally:
            self.live = False
            if self.writer is not None:
                self.writer.close()

    def _bounce_unencodable(self, m: Message, e: Exception) -> None:
        if m.direction != Direction.RESPONSE:
            from ..core.message import make_error_response
            self.client.deliver(make_error_response(
                m, SiloUnavailableError(
                    f"wire encode failed for "
                    f"{m.interface_name}.{m.method_name}: {e}")))

    async def _send_loop(self) -> None:
        from ..observability.profiling import mark_loop_category
        mark_loop_category("client")  # see _pump: client-side machinery
        while True:
            msg = await self.queue.get()
            batch = _drain_batch(self.queue, msg)
            chunks = encode_message_batch(batch, self._bounce_unencodable,
                                          native=self.peer_native)
            if not chunks:
                continue
            try:
                assert self.writer is not None
                self.writer.write(b"".join(chunks))
                await self.writer.drain()
            except (OSError, AssertionError) as e:
                self.live = False
                log.warning("gateway %s send failed: %s", self.endpoint, e)
                # the connection is known-dead: fail EVERY batched call
                # promptly instead of letting any wait out the response
                # timeout
                from ..core.message import make_error_response
                for m in batch:
                    if m.direction != Direction.RESPONSE:
                        self.client.deliver(make_error_response(
                            m, SiloUnavailableError(
                                f"gateway {self.endpoint} connection lost")))

    def close(self) -> None:
        self.live = False
        for t in (self.reader_task, self.sender_task):
            if t is not None:
                t.cancel()
        if self.writer is not None:
            self.writer.close()


class GatewayClient(RuntimeClient):
    """Out-of-process cluster client over TCP gateways
    (OutsideRuntimeClient.cs:22 + GatewayManager.cs): N gateway connections,
    per-grain affinity routing with round-robin fallback, response pump,
    reconnect-on-demand."""

    def __init__(self, gateways: list[str], response_timeout: float = 30.0):
        super().__init__(response_timeout=response_timeout)
        if not gateways:
            raise ValueError("at least one gateway endpoint required")
        self.generation = _fresh_generation()
        self.conns = [_GatewayConnection(self, ep) for ep in gateways]
        self.grain_factory = GrainFactory(self)
        self._rr = 0
        self.connected = False
        self._reconnect_period = 0.5
        self._reconnector: asyncio.Task | None = None
        from .observers import ObserverHost
        self._observer_host = ObserverHost(lambda: self.silo_address)

    # -- RuntimeClient surface --------------------------------------------
    @property
    def silo_address(self) -> SiloAddress | None:
        live = self._live()
        return live[0].pseudo_address if live else None

    def _live(self) -> list[_GatewayConnection]:
        return [c for c in self.conns if c.live]

    def _pick_conn(self, msg: Message, live: list) -> _GatewayConnection:
        """The ONE affinity rule for both transmit paths: per-grain hash
        keeps one grain's requests ordered through one connection,
        round-robin for untargeted traffic."""
        if msg.target_grain is not None:
            return live[msg.target_grain.uniform_hash % len(live)]
        self._rr = (self._rr + 1) % len(live)
        return live[self._rr]

    def transmit(self, msg: Message) -> None:
        self._mark_remote_trace(msg)  # client sends always leave the client
        live = self._live()
        if not live:
            raise SiloUnavailableError("no live gateway connections")
        conn = self._pick_conn(msg, live)
        msg.sending_silo = conn.pseudo_address
        conn.queue.put_nowait(msg)

    def transmit_batch(self, msgs: list) -> None:
        """Batched transmit (RuntimeClient.call_batch): the group is
        split per live connection by the same affinity rule as
        ``transmit`` (shared ``_pick_conn``) and each slice is queued in
        one synchronous pass — the sender task wakes once and the whole
        slice rides a single ``encode_message_batch`` write (deliberate
        wire-batch fill, not greedy-drain luck)."""
        live = self._live()
        if not live:
            raise SiloUnavailableError("no live gateway connections")
        for msg in msgs:
            self._mark_remote_trace(msg)
            conn = self._pick_conn(msg, live)
            msg.sending_silo = conn.pseudo_address
            conn.queue.put_nowait(msg)

    def deliver(self, msg: Message) -> None:
        if msg.direction == Direction.RESPONSE:
            self.receive_response(msg)
        elif self._observer_host.dispatch(msg):
            pass  # grain→client observer notification
        else:
            log.debug("gateway client dropping unexpected message %s",
                      msg.method_name)

    # -- observers (CreateObjectReference / DeleteObjectReference) ---------
    def create_observer(self, obj):
        """Observer routes pin to the pseudo address of the connection the
        ref was minted on; if that gateway drops, re-create the observer
        (the reference refreshes observer routes the same way —
        ClientObserverRegistrar re-registration)."""
        return self._observer_host.create_observer(obj)

    def delete_observer(self, ref) -> bool:
        return self._observer_host.delete_observer(ref)

    # -- lifecycle ---------------------------------------------------------
    async def connect(self) -> "GatewayClient":
        results = await asyncio.gather(
            *(c.connect() for c in self.conns), return_exceptions=True)
        if not self._live():
            raise SiloUnavailableError(
                f"could not reach any gateway: {results}")
        self.connected = True
        self._reconnector = asyncio.get_running_loop().create_task(
            self._reconnect_loop())
        return self

    async def _reconnect_loop(self) -> None:
        """Revive dropped gateway connections (GatewayManager keeps retrying
        dead gateways and returns them to rotation when reachable)."""
        from ..observability.profiling import mark_loop_category
        mark_loop_category("client")  # see _pump: client-side machinery
        while True:
            await asyncio.sleep(self._reconnect_period)
            for c in self.conns:
                if not c.live:
                    c.close()  # reap stale pump/sender tasks
                    try:
                        await c.connect()
                        log.info("gateway %s reconnected", c.endpoint)
                    except OSError:
                        pass  # still down; retry next period

    async def close_async(self) -> None:
        if self._reconnector is not None:
            self._reconnector.cancel()
            self._reconnector = None
        for c in self.conns:
            c.close()
        self.connected = False
        self.close()

    def get_grain(self, grain_class: type, key, key_ext: str | None = None):
        return self.grain_factory.get_grain(grain_class, key, key_ext)
