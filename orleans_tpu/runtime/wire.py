"""Wire framing for cross-process messaging (L2 wire tier).

Re-design of the reference's framing layer: length-prefixed
``[4B headers-len][4B body-len][headers][body]`` frames
(/root/reference/src/Orleans.Core/Messaging/IncomingMessageBuffer.cs:125-163,
``Message.LENGTH_HEADER_SIZE`` Message.cs:14-15, ``Message.Serialize:481``).

Departures from the reference:

* Headers and body are encoded with the wire tier of
  :mod:`orleans_tpu.core.serialization` (restricted-unpickler codec with a
  type allowlist) instead of the token-stream binary format — the hot data
  path on TPU never touches this codec (vectorized payloads ride device
  collectives; see orleans_tpu.parallel.transport), so the control plane
  optimizes for fidelity over bytes.
* ``expires_at`` is a ``time.monotonic`` stamp, meaningless across process
  boundaries — it is rebased through a relative TTL carried on the wire.
* A connection opens with a handshake frame identifying the peer
  (``kind`` silo/client + its SiloAddress) — the analog of the gateway
  handshake-carried client id (GatewayAcceptor.cs:63,
  ClientMessageCenter.cs:453).
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Any

log = logging.getLogger("orleans.wire")

from ..core import message as _msg_mod
from ..core.ids import SiloAddress
from ..core.message import Message
from ..core.serialization import deserialize, serialize, serialize_portable
from ..observability.stats import COUNT_BOUNDS as _COUNT_BOUNDS
from ..observability.stats import EGRESS_STATS as _EGRESS
from ..observability.stats import INGEST_STATS as _INGEST
from ..observability.stats import SIZE_BOUNDS as _SIZE_BOUNDS

_EGRESS_ENCODE = _EGRESS["encode"]
_DECODE_SECONDS = _INGEST["decode"]
_DECODE_BYTES = _INGEST["decode_bytes"]
_FRAMES = _INGEST["frames"]
_FRAME_BATCH = _INGEST["frame_batch"]

__all__ = [
    "MAX_FRAME_SEGMENT", "FrameError", "WireDecodeError",
    "encode_frame", "read_frame", "frame_stream",
    "encode_message", "decode_message",
    "encode_message_batch", "decode_frames", "finish_batch_entries",
    "writev_leftover",
    "encode_handshake", "decode_handshake",
]

_LEN = struct.Struct("<II")  # headers-len, body-len (LENGTH_HEADER_SIZE = 8)

# Refuse absurd frames before allocating (the reference caps via
# MaxMessageBodySize / buffer-pool discipline).
MAX_FRAME_SEGMENT = 128 * 1024 * 1024


class FrameError(Exception):
    """Malformed or oversized frame — the connection must be dropped."""


class WireDecodeError(Exception):
    """Frame arrived intact but its payload failed to decode (unregistered
    type, version skew). Scoped to one message, not the connection."""


def encode_frame(headers: bytes, body: bytes) -> bytes:
    if len(headers) > MAX_FRAME_SEGMENT or len(body) > MAX_FRAME_SEGMENT:
        raise FrameError(
            f"frame segment exceeds {MAX_FRAME_SEGMENT} bytes "
            f"(headers={len(headers)}, body={len(body)})")
    return _LEN.pack(len(headers), len(body)) + headers + body


async def read_frame(reader: asyncio.StreamReader) -> tuple[bytes, bytes]:
    """Read one complete frame; raises IncompleteReadError at clean EOF."""
    prefix = await reader.readexactly(_LEN.size)
    hlen, blen = _LEN.unpack(prefix)
    if hlen > MAX_FRAME_SEGMENT or blen > MAX_FRAME_SEGMENT:
        raise FrameError(f"oversized frame announced: {hlen}+{blen}")
    headers = await reader.readexactly(hlen) if hlen else b""
    body = await reader.readexactly(blen) if blen else b""
    return headers, body


async def frame_stream(reader: asyncio.StreamReader, chunk_size: int = 1 << 16,
                       on_batch=None):
    """Yield (headers, body) frames from a buffered chunk reader.

    The per-frame path (`read_frame`) costs three readexactly awaits per
    message; under load this reads a socket chunk once and parses every
    complete frame out of it (the IncomingMessageBuffer batching,
    IncomingMessageBuffer.cs:125). Ends cleanly at EOF on a frame
    boundary; raises IncompleteReadError for a mid-frame EOF and
    FrameError for an oversized announcement (connection must drop).

    ``on_batch`` (metrics): called with the number of complete frames
    parsed out of each socket read — the receive-side batching-degree
    signal (frames-per-wakeup ≈ how hard the sender/backlog is driving
    this link)."""
    buf = bytearray()
    pos = 0
    while True:
        end = len(buf)
        n_frames = 0
        while end - pos >= 8:
            hlen, blen = _LEN.unpack_from(buf, pos)
            if hlen > MAX_FRAME_SEGMENT or blen > MAX_FRAME_SEGMENT:
                raise FrameError(f"oversized frame announced: {hlen}+{blen}")
            total = 8 + hlen + blen
            if end - pos < total:
                break
            h0 = pos + 8
            yield bytes(buf[h0:h0 + hlen]), bytes(buf[h0 + hlen:pos + total])
            pos += total
            n_frames += 1
        if on_batch is not None and n_frames:
            on_batch(n_frames)
        if pos:
            del buf[:pos]
            pos = 0
        chunk = await reader.read(chunk_size)
        if not chunk:
            if buf:
                raise asyncio.IncompleteReadError(bytes(buf), None)
            return
        buf += chunk


# ---------------------------------------------------------------------------
# Message <-> frame
# ---------------------------------------------------------------------------

# Every Message slot except the lazily-decoded body (the headers/body split
# of Message.HeadersContainer, Message.cs:725), expires_at (rebased),
# received_at (a local monotonic arrival stamp, meaningless cross-process —
# the receiver re-stamps on delivery), and _pool_free/_pool_gen (freelist
# bookkeeping, core.message.recycle_message).
_HEADER_SLOTS = tuple(s for s in Message.__slots__
                      if s not in ("body", "expires_at", "received_at",
                                   "_pool_free", "_pool_gen"))

# Enum-typed header fields ride the wire as plain ints (the native codec's
# scalar fast path; pickling an IntEnum writes a by-reference class lookup).
from ..core import serialization as _ser  # noqa: E402
from ..core.message import Category, Direction, RejectionType, ResponseKind  # noqa: E402

_I_CATEGORY = _HEADER_SLOTS.index("category")
_I_DIRECTION = _HEADER_SLOTS.index("direction")
_I_RESPONSE_KIND = _HEADER_SLOTS.index("response_kind")
_I_REJECTION_TYPE = _HEADER_SLOTS.index("rejection_type")


# (field index, members-indexed-by-value) pairs: the single source of truth
# for enum-typed header fields, consumed by the native decoder directly and
# by the pickle-fallback paths below.
_ENUM_SPEC = (
    (_I_CATEGORY, _ser.members_by_value(Category)),
    (_I_DIRECTION, _ser.members_by_value(Direction)),
    (_I_RESPONSE_KIND, _ser.members_by_value(ResponseKind)),
    (_I_REJECTION_TYPE, _ser.members_by_value(RejectionType)),
)

# Native header-struct codec (hotwire.c configure_headers/pack_frame/
# unpack_header): the field-name tuple and enum spec are cached inside the
# C module once, so the per-message socket path is a single C call each
# way — no struct.pack, no bytes concat, no spec tuples crossing the
# C boundary per frame. Frame BYTES are identical to the pack_attrs form,
# so mixed builds (one side without the new entry points) interoperate.
_HW_FRAMES = _ser._hotwire is not None and \
    hasattr(_ser._hotwire, "pack_frame")
if _HW_FRAMES:
    _ser._hotwire.configure_headers(_HEADER_SLOTS, _ENUM_SPEC)
# Vectorized frame-batch entry points (hotwire.c pack_batch/unpack_batch):
# one C call per send batch / per socket read instead of one per frame.
# Batch BYTES are identical to the per-frame form (pack_batch output ==
# concatenated pack_frame frames; unpack_batch parses either), so every
# mix of batched/per-frame/pickle peers interoperates.
_HW_BATCH = _HW_FRAMES and hasattr(_ser._hotwire, "pack_batch")
# Header-prefix template mode (hotwire.c make_header_template/
# pack_batch_tmpl): responses within one egress group share an invariant
# header prefix per (sending-silo, target-silo, kind); the template
# memcpys the pre-encoded invariant runs and patches only the varying
# fields — byte-identical to pack_frame (property-tested).
_HW_TMPL = _HW_BATCH and hasattr(_ser._hotwire, "pack_batch_tmpl")

# The per-message (varying) header fields of a templated frame:
# correlation id, the grain/activation endpoints, the per-class method
# identity, the result discriminator, and the per-message stamps
# (trace-context wall stamp from _stamp_response / call_batch req_ctx,
# txn joins from _attach_txn_joins) — everything else is invariant
# across one template key and rides the memcpy'd chunks. ONE index set
# serves responses AND requests (the call_batch sender half): a field
# that is invariant within a request batch but varies across batches
# (method identity, sender grain) simply encodes per message, which is
# always byte-correct. Sampled frames batch IDENTICALLY (their
# request_context is a varying field); only headers the template cannot
# carry — rejections, forwarded/resent envelopes — peel to the
# per-frame encoder below.
_TMPL_VAR_SLOTS = frozenset((
    "id", "sending_grain", "sending_activation", "target_grain",
    "target_activation", "interface_name", "method_name", "response_kind",
    "is_read_only", "request_context", "transaction_info",
    "interface_version"))
_TMPL_VAR_IDX = tuple(i for i, s in enumerate(_HEADER_SLOTS)
                      if s in _TMPL_VAR_SLOTS)

# template key -> pre-encoded chunk tuple. Response keys are
# (sending_silo, target_silo, category); request keys additionally pin
# direction and the invariant flags (see _frame_template; chain-carrying
# envelopes peel, so chains never enter the key space). Bounded: a
# cluster only ever sees O(silos + clients) keys, but a pathological key
# churn (client generations) must not grow it forever. This dict is the
# MAIN-loop cache; egress shards (runtime.multiloop.EgressShard) pass
# their own per-shard dict through ``encode_message_batch(tmpl_cache=)``
# so shard-side encode never touches (or contends on) this one — the
# key space and cap are identical either way.
_TMPL_CACHE: dict = {}
_TMPL_CACHE_CAP = 512


def _frame_template(m: Message, cache: dict | None = None):
    """The cached header-prefix template for ``m``, or None when the
    message must take the per-frame encoder (carrying headers the
    template's invariant runs can't represent).

    Responses key on (sending_silo, target_silo, category) exactly as
    the PR-10 response template did. Requests/one-ways — the open
    PR-3/PR-10 half, landed for the ``call_batch`` native sender — key
    additionally on direction and the flag fields that are constant per
    (class, method) batch (is_always_interleave, immutable), which
    subsumes the per-(sender, target-class, method) keying: one
    template serves every method a sender batches over one link, since
    method identity is a varying field. Chain-CARRYING envelopes peel
    (requests and responses alike): a chain would have to be part of
    the key, and chain cardinality scales with active calling grains —
    keying on it would thrash the bounded cache and evict the hot
    response templates; client senders (the call_batch target) carry
    empty chains and template fully.

    ``cache`` (default: the module-level main-loop cache): the bounded
    template dict to consult — egress shards pass their own so two
    loops never share one dict (the pre-encoded chunk tuples themselves
    are immutable and the C entry points hold the GIL throughout, so
    the only shared state to confine was the cache)."""
    if cache is None:
        cache = _TMPL_CACHE
    d = m.direction
    if (m.rejection_type is not None or m.rejection_info is not None
            or m.forward_count or m.resend_count or m.is_unordered
            or m.call_chain
            or m.cache_invalidation is not None or m.is_new_placement):
        return None  # peel: headers outside the invariant constants
    if d == Direction.RESPONSE:
        if m.is_always_interleave or not m.immutable:
            return None  # peel: same response semantics as PR 10
        key = (m.sending_silo, m.target_silo, m.category)
    else:
        # REQUEST / ONE_WAY: flags are invariant within one call_batch
        # group, so they ride the template keyed, not peeled
        key = (m.sending_silo, m.target_silo, m.category, d,
               m.is_always_interleave, m.immutable)
    t = cache.get(key)
    if t is None:
        if len(cache) >= _TMPL_CACHE_CAP:
            cache.clear()
        try:
            t = cache[key] = _ser._hotwire.make_header_template(
                m, _TMPL_VAR_IDX)
        except Exception:  # noqa: BLE001 — unencodable invariant field:
            return None    # the per-frame path owns the error semantics
    return t


_NO_RUN = object()  # run-splitting sentinel (a template is never this)


def encode_message(msg: Message, native: bool = True) -> bytes:
    """Encode one message frame. ``native=False`` forces the pickle wire
    form — used per-connection when the peer's handshake did not advertise
    hotwire support (mixed-build cluster: a silo whose native build failed
    must still receive decodable frames; SerializationManager.cs:173-201
    negotiates serializers per registered type, we negotiate per link)."""
    if _msg_mod._DEBUG_POOL:
        # pool poisoning: serializing a recycled shell would put another
        # call's (or zeroed) headers on the wire — fail loudly instead
        _msg_mod.assert_live(msg, "wire.encode_message")
    ttl = None
    if msg.expires_at is not None:
        ttl = max(0.0, msg.expires_at - time.monotonic())
    body = serialize(msg.body) if native else serialize_portable(msg.body)
    hw = _ser._hotwire if native else None
    if hw is not None and _HW_FRAMES:
        try:
            # single C call for the whole frame: getattr walk + enum
            # coercion + header encode + length prefix + body splice
            return hw.pack_frame(msg, ttl, body)
        except ValueError:
            pass  # cyclic/over-deep header payload (or absurd size):
            #       the pickle/encode_frame fallback below handles/raises
    headers = None
    if hw is not None:
        try:
            # single C call: getattr walk + enum coercion + encode
            headers = hw.pack_attrs(msg, _HEADER_SLOTS, ttl)
        except ValueError:
            pass  # cyclic/over-deep header payload: pickle's memo handles it
    if headers is None:
        fields = [getattr(msg, s) for s in _HEADER_SLOTS]
        for i, _members in _ENUM_SPEC:
            if fields[i] is not None:
                fields[i] = int(fields[i])
        headers = serialize((tuple(fields), ttl)) if native \
            else serialize_portable((tuple(fields), ttl))
    return encode_frame(headers, body)


def decode_message(headers: bytes, body: bytes, stats=None) -> Message:
    """Decode one frame into a Message. ``stats`` (a StatsRegistry, passed
    by metrics-enabled receive paths) times the whole decode — native
    hotwire or pickle fallback alike — into the ingest stage histograms
    and stamps the envelope's ``received_at`` with the post-decode
    monotonic clock, the single stamp every later ingest stage measures
    against (and re-stamps at its own boundary)."""
    t0 = time.monotonic() if stats is not None else 0.0
    msg = Message.__new__(Message)
    try:
        if headers[:1] == b"\xa7" and _HW_FRAMES and \
                _ser._hotwire is not None:
            # single C call against the cached header spec
            ttl = _ser._hotwire.unpack_header(headers, msg)
        elif headers[:1] == b"\xa7" and _ser._hotwire is not None:
            # single C call: decode + enum restore + setattr walk
            ttl = _ser._hotwire.unpack_attrs(
                headers, msg, _HEADER_SLOTS, _ENUM_SPEC)
        else:
            fields, ttl = deserialize(headers)
            fields = list(fields)
            for i, members in _ENUM_SPEC:
                v = fields[i]
                if v is not None:
                    # range-check before indexing: a negative value must be
                    # rejected, not wrap to the last member, and bool is
                    # not an enum value (matches the C decoder's ev < 0
                    # guard and its exact-int check)
                    m = members[v] if type(v) is int and \
                        0 <= v < len(members) else None
                    if m is None:
                        raise ValueError(
                            f"bad enum value {v!r} for header {_HEADER_SLOTS[i]}")
                    fields[i] = m
            for k, v in zip(_HEADER_SLOTS, fields, strict=True):
                setattr(msg, k, v)
    except Exception as e:  # noqa: BLE001 — headers must decode or the msg is lost
        raise WireDecodeError(f"undecodable message headers: {e}") from e
    msg.expires_at = None if ttl is None else time.monotonic() + ttl
    msg.received_at = None  # local arrival stamp; tracing re-stamps
    msg._pool_free = False  # full slot set: consumers may walk __slots__
    msg._pool_gen = 0       # fresh incarnation on this process
    try:
        msg.body = deserialize(body)
    except Exception as e:  # noqa: BLE001 — body failure is per-message
        msg.body = None
        raise _BodyDecodeError(msg, e) from e
    if stats is not None:
        now = time.monotonic()
        stats.observe(_DECODE_SECONDS, now - t0)
        stats.histogram_with(_DECODE_BYTES, _SIZE_BOUNDS).observe(
            len(headers) + len(body))
        stats.increment(_FRAMES)
        msg.received_at = now  # ingest stage stamp (enqueue measures next)
    return msg


class _BodyDecodeError(WireDecodeError):
    """Body failed to decode but headers did: carries the headers-only
    message so the receiver can still route an error response."""

    def __init__(self, msg: Message, cause: Exception):
        super().__init__(f"undecodable message body: {cause}")
        self.message = msg


# ---------------------------------------------------------------------------
# Frame batches (the batched-ingress wire unit)
# ---------------------------------------------------------------------------

def encode_message_batch(msgs: list, bounce, native: bool = True,
                         stats=None, templates: bool = True,
                         tmpl_cache: dict | None = None) -> list:
    """Encode a send batch into wire chunks: contiguous frame-batch
    buffers (``pack_batch`` C calls) on the native path, else one chunk
    per message. Per-message encode failures route to ``bounce`` (scoped
    to the message, never the connection), matching
    :func:`encode_message`; a batch-level native failure falls back to the
    per-message path so the failing message is identified and bounced
    alone. Output bytes are identical either way.

    ``templates`` (native path only): contiguous runs of messages whose
    headers a cached prefix template can carry encode via
    ``pack_batch_tmpl`` — the invariant header runs are memcpy'd and only
    correlation id / endpoints / stamps / body splice encode per message
    (the PR-3 SocketManager pooled-buffer carry-over). Responses AND
    requests ride it: the request-side template is the ``call_batch``
    native-sender half (keyed per sender link, method
    identity varying — see :func:`_frame_template`). ``stats``
    (metrics-enabled egress writers): the whole batch encode is timed as
    one ``egress.encode.seconds`` observation — MAIN-loop callers only;
    shard-side egress writers pass ``stats=None`` and stamp the encode
    themselves for loop-side replay (the registries are loop-confined).
    ``tmpl_cache``: the per-loop template dict (see
    :func:`_frame_template`; None = the main-loop cache).
    """
    hw = _ser._hotwire if native else None
    if hw is not None and _HW_BATCH:
        now = time.monotonic()
        use_tmpl = templates and _HW_TMPL
        # ordered (template | None, items) runs: FIFO on the wire is
        # preserved because runs flush in arrival order
        runs: list = []
        cur_t = _NO_RUN
        cur_items: list = []
        for m in msgs:
            try:
                if _msg_mod._DEBUG_POOL:
                    # inside the try: a poisoned envelope bounces like any
                    # other per-message failure (the per-frame path's
                    # behavior) instead of killing the sender task
                    _msg_mod.assert_live(m, "wire.encode_message_batch")
                ttl = None
                if m.expires_at is not None:
                    ttl = max(0.0, m.expires_at - now)
                body = serialize(m.body)
                tmpl = _frame_template(m, tmpl_cache) if use_tmpl else None
            except Exception as e:  # noqa: BLE001 — per-message body failure
                bounce(m, e)
                continue
            if tmpl is not cur_t:
                cur_items = []
                runs.append((tmpl, cur_items))
                cur_t = tmpl
            cur_items.append((m, ttl, body))
        chunks = []
        for tmpl, items in runs:
            try:
                if tmpl is None:
                    chunks.append(hw.pack_batch(items))
                else:
                    chunks.append(hw.pack_batch_tmpl(
                        tmpl, _TMPL_VAR_IDX, items))
            except Exception:  # noqa: BLE001 — a header refused batch
                # encode: retry per-message so the failure scopes to one
                # frame (bodies re-serialize; this path is rare)
                for m, _ttl, _body in items:
                    try:
                        chunks.append(encode_message(m, native=native))
                    except Exception as e:  # noqa: BLE001
                        bounce(m, e)
        if stats is not None and chunks:
            stats.observe(_EGRESS_ENCODE, time.monotonic() - now)
        return chunks
    chunks = []
    for m in msgs:
        try:
            chunks.append(encode_message(m, native=native))
        except Exception as e:  # noqa: BLE001 — per-message, not the link
            bounce(m, e)
    return chunks


def finish_batch_entries(entries, msgs: list, bounces: list) -> None:
    """Shared tail of the native batch decode (``unpack_batch`` and the
    vectored pump's ``sock_recv_batch``): per entry, rebase the TTL,
    initialise the wire-excluded pool slots, and deserialize the body —
    pickle-peer (or corrupt-native) frames carry raw header/body
    segments and fall through the ordinary per-frame
    :func:`decode_message`, which reproduces the exact per-message error
    semantics. Appends to ``msgs``/``bounces`` in wire order; callers
    own the ``received_at`` stamping."""
    for msg, ttl, body in entries:
        if msg is None:
            # pickle-peer (or corrupt-native) frame: ttl/body carry the
            # raw header/body segments — ordinary per-frame decode
            try:
                msgs.append(decode_message(ttl, body))
            except _BodyDecodeError as e:
                bounces.append(e)
            except WireDecodeError as e:
                log.warning("dropping message with undecodable "
                            "headers: %s", e)
            continue
        msg.expires_at = None if ttl is None else time.monotonic() + ttl
        msg.received_at = None  # callers stamp once per batch
        msg._pool_free = False  # full slot set (see decode_message)
        msg._pool_gen = 0
        try:
            msg.body = deserialize(body)
        except Exception as e:  # noqa: BLE001 — body failure per-message
            msg.body = None
            bounces.append(_BodyDecodeError(msg, e))
            continue
        msgs.append(msg)


def decode_frames(buf, stats=None) -> tuple[int, list, list]:
    """Parse every COMPLETE frame out of one receive buffer in a single
    pass: returns ``(consumed, msgs, bounces)``. ``consumed`` is how many
    bytes were fully parsed (the caller keeps the partial tail for the
    next socket read); ``bounces`` are :class:`_BodyDecodeError`\\ s whose
    headers survived (route an error back); header-undecodable frames are
    dropped with a log, exactly like the per-frame path.

    Native path: ONE ``unpack_batch`` C call decodes every hotwire frame
    straight into blank Message shells; pickle-peer frames in the same
    buffer fall through to :func:`decode_message`. Fallback path
    (``ORLEANS_TPU_NATIVE=0`` or no toolchain): Python length-prefix walk
    + per-frame :func:`decode_message` — the wire bytes are identical, so
    mixed-build peers interoperate frame for frame.

    ``stats`` (metrics-enabled receive paths): the whole batch decode is
    timed as one ``decode`` observation (stage *sums* stay truthful — the
    share math divides summed seconds), ``decode_bytes`` observes the
    consumed byte count, ``frames`` counts messages, and the per-read
    batching degree lands in ``frame_batch``. Every decoded envelope is
    stamped with the same post-decode ``received_at``."""
    t0 = time.monotonic() if stats is not None else 0.0
    msgs: list[Message] = []
    bounces: list[_BodyDecodeError] = []
    consumed = 0
    if _HW_BATCH and _ser._hotwire is not None:
        try:
            consumed, entries = _ser._hotwire.unpack_batch(buf, Message)
        except ValueError as e:
            # oversized/hostile frame announcement: connection must drop
            raise FrameError(str(e)) from e
        finish_batch_entries(entries, msgs, bounces)
    else:
        end = len(buf)
        pos = 0
        while end - pos >= 8:
            hlen, blen = _LEN.unpack_from(buf, pos)
            if hlen > MAX_FRAME_SEGMENT or blen > MAX_FRAME_SEGMENT:
                if pos > 0:
                    # deliver the frames parsed ahead of the hostile
                    # announcement (per-frame parity); the next call sees
                    # it at position 0 and raises then
                    break
                raise FrameError(f"oversized frame announced: {hlen}+{blen}")
            total = 8 + hlen + blen
            if end - pos < total:
                break
            h0 = pos + 8
            headers = bytes(buf[h0:h0 + hlen])
            body = bytes(buf[h0 + hlen:pos + total])
            pos += total
            try:
                msgs.append(decode_message(headers, body))
            except _BodyDecodeError as e:
                bounces.append(e)
            except WireDecodeError as e:
                log.warning("dropping message with undecodable headers: %s",
                            e)
        consumed = pos
    if stats is not None and (msgs or bounces):
        now = time.monotonic()
        n = len(msgs) + len(bounces)
        stats.observe(_DECODE_SECONDS, now - t0)
        stats.histogram_with(_DECODE_BYTES, _SIZE_BOUNDS).observe(consumed)
        stats.increment(_FRAMES, n)
        stats.histogram_with(_FRAME_BATCH, _COUNT_BOUNDS).observe(n)
        for m in msgs:
            m.received_at = now
        for e in bounces:
            e.message.received_at = now
    return consumed, msgs, bounces


# ---------------------------------------------------------------------------
# Handshake
# ---------------------------------------------------------------------------

def writev_leftover(chunks: list, sent: int) -> bytes:
    """The unsent suffix of a chunk list after a (possibly partial)
    vectored ``sock_writev`` — shared by every vectored egress drain
    (ShardWriter, the silo-peer sender)."""
    total = 0
    for i, c in enumerate(chunks):
        nxt = total + len(c)
        if sent < nxt:
            rest = [c[sent - total:]]
            rest.extend(chunks[i + 1:])
            return b"".join(rest)
        total = nxt
    return b""


def leads_hostile_frame(buf) -> bool:
    """True when the buffer's leading length prefix announces an
    oversized frame. :func:`decode_frames` stops BEFORE such a prefix
    when valid frames precede it (so they are still delivered) — the
    receive pump calls this afterwards to drop the link immediately
    instead of waiting for the hostile peer's next (never-coming)
    bytes."""
    if len(buf) < 8:
        return False
    hlen, blen = _LEN.unpack_from(buf, 0)
    return hlen > MAX_FRAME_SEGMENT or blen > MAX_FRAME_SEGMENT


def encode_handshake(kind: str, address: SiloAddress,
                     extra: dict[str, Any] | None = None) -> bytes:
    """Handshake frames are ALWAYS pickle-encoded: the handshake is where
    codec support is negotiated, so it must be decodable by every build —
    a hotwire-encoded handshake would be unreadable to exactly the peers
    the negotiation exists for. Advertises this process's codec support
    (``hotwire``); each side then encodes per-connection at the peer's
    level (the connection-preamble negotiation the reference does for
    serializer registration, SerializationManager.cs:173-201)."""
    payload = {"kind": kind, "address": address,
               "hotwire": _ser._hotwire is not None, **(extra or {})}
    return encode_frame(serialize_portable(payload), b"")


def decode_handshake(headers: bytes) -> dict[str, Any]:
    hs = deserialize(headers)
    if not isinstance(hs, dict) or "kind" not in hs or "address" not in hs:
        raise FrameError(f"malformed handshake: {hs!r}")
    return hs
