"""RuntimeClient: the RPC engine shared by silo-interior and external clients.

Re-design of /root/reference/src/Orleans.Runtime/Core/InsideRuntimeClient.cs:28
(``SendRequest:120-229`` with callback registry :207-217, ``Invoke:294-474``,
``ReceiveResponse:569-627``, ``BreakOutstandingMessagesToDeadSilo:726``) and
``CallbackData`` (Core/Runtime/CallbackData.cs).
"""

from __future__ import annotations

import asyncio
import random
import logging
import time
from typing import TYPE_CHECKING

from ..core.errors import (
    GrainCallTimeoutError,
    RejectionError,
    SiloUnavailableError,
)
from ..core.ids import GrainId, SiloAddress
from ..core import message as _msg_mod
from ..core.message import (
    Category,
    Direction,
    Message,
    ResponseKind,
    make_request_fast,
    recycle_message,
    recycle_messages,
)
from ..core.serialization import copy_call_body, deep_copy
from ..observability.tracing import (
    TRACE_KEY,
    context_from_headers,
    current_trace,
    mark_remote_if_traced,
    pending_root_link,
)
from .cancellation import register_outgoing_tokens
from .context import (
    TXN_KEY,
    RequestContext,
    build_call_chain,
    current_activation,
)

if TYPE_CHECKING:
    from .activation import ActivationData

log = logging.getLogger("orleans.rpc")

MAX_RESEND_COUNT = 3  # SiloMessagingOptions.MaxResendCount analog


async def _finish_span_after(tracer, span, res):
    """Close the client span when the RPC settles (success or error) —
    the span covers the full round trip including transparent resends."""
    try:
        result = await res
    except BaseException as e:
        tracer.close(span, error=type(e).__name__)
        raise
    tracer.close(span)
    return result


def _resolve_future(fut: asyncio.Future, value, exc) -> None:
    if fut.done():
        return  # timed out / broken / cancelled while deferred
    if exc is not None:
        fut.set_exception(exc)
    else:
        fut.set_result(value)


class CallbackData:
    """One outstanding request: future + timeout bookkeeping (CallbackData.cs).
    ``txn_info`` is the caller's ambient TransactionInfo (if any) so
    callee joins piggybacked on the response can merge back into it.
    ``gen`` is the request shell's pool generation captured at registration
    (debug pool-poisoning only, ORLEANS_TPU_DEBUG_POOL=1): the shell must
    still be that incarnation when the response correlates back.
    ``span`` is the still-open client invoke span for sampled calls (None
    otherwise) so rejection/resend events can attach to it mid-flight."""

    __slots__ = ("message", "future", "deadline", "txn_info", "gen", "span")

    def __init__(self, message: Message, future: asyncio.Future,
                 deadline: float | None, txn_info=None, span=None):
        self.message = message
        self.future = future
        self.deadline = deadline
        self.txn_info = txn_info
        self.gen = None
        self.span = span


# CallbackData freelist (the BufferPool.cs discipline): one acquired per
# round-trip RPC, released wherever the entry leaves the registry for good.
_CB_POOL: list[CallbackData] = []
_CB_POOL_CAP = 1024


def _fresh_callback(message: Message, future: asyncio.Future,
                    deadline: float | None, txn_info,
                    span=None) -> CallbackData:
    pool = _CB_POOL
    if pool:
        cb = pool.pop()
        cb.message = message
        cb.future = future
        cb.deadline = deadline
        cb.txn_info = txn_info
        cb.gen = _msg_mod.pool_generation(message) \
            if _msg_mod._DEBUG_POOL else None
        cb.span = span
        return cb
    cb = CallbackData(message, future, deadline, txn_info, span)
    if _msg_mod._DEBUG_POOL:
        cb.gen = _msg_mod.pool_generation(message)
    return cb


def _recycle_callback(cb: CallbackData) -> None:
    cb.message = None
    cb.future = None
    cb.txn_info = None
    cb.span = None
    if len(_CB_POOL) < _CB_POOL_CAP:
        _CB_POOL.append(cb)


class RuntimeClient:
    """Shared base: callback registry + response correlation. Subclassed by
    the silo interior (:class:`InsideRuntimeClient`) and the external client
    (orleans_tpu.runtime.client.ClusterClient)."""

    def __init__(self, response_timeout: float = 30.0):
        self.callbacks: dict[int, CallbackData] = {}
        self.response_timeout = response_timeout
        self._timeout_sweeper: asyncio.Task | None = None
        # outgoing call filter chain (IOutgoingGrainCallFilter; silo-side
        # registration via SiloBuilder.add_outgoing_call_filter, client-side
        # via ClusterClient.add_outgoing_call_filter)
        self.outgoing_call_filters: list = []
        self._filter_tasks: set[asyncio.Task] = set()
        # distributed-tracing collector (observability.tracing): None on
        # the hot path unless tracing is enabled — silo-side wired from
        # SiloConfig.trace_*, client-side via enable_tracing()
        self.tracer = None
        # hot-lane dispatch (runtime.hotlane): hit/fallback counter pair
        # (DISPATCH_STATS) as plain ints — a StatsRegistry increment per
        # call was itself measurable in the r5 attribution — plus an
        # on/off switch (benchmarks and the perf floor flip it to measure
        # the messaging path alone)
        self.hot_hits = 0
        self.hot_fallbacks = 0
        self.hot_lane_enabled = True
        # batch-aware fairness (hotlane._hot_turn): collapsed turns since
        # the last event-loop yield — bounds the forced-yield cadence when
        # the loop has nothing else ready
        self.hot_calls_since_yield = 0
        # batched response correlation (receive_response_batch): the
        # client-side half of the batched-egress A/B lever — off restores
        # per-message receive_response for every delivered batch
        self.batched_egress = True

    def enable_tracing(self, sample_rate: float = 1.0,
                       buffer_size: int = 4096, name: str = "client", *,
                       tail: bool = False, tail_window: float = 0.25,
                       slow_threshold: float | None = None,
                       slow_percentile: float | None = None,
                       auto_threshold: bool = False,
                       leg_ttl: float | None = None,
                       max_pending: int = 256,
                       policy=None, otlp_endpoint: str | None = None):
        """Install a SpanCollector so calls through this client open
        root client spans (head-based sampling at ``sample_rate``).
        ``tail=True`` defers keep/drop to trace completion (slow/errored/
        forced survive — see TracingOptions.tail_*); ``auto_threshold``
        self-tunes the slow threshold from the root-duration percentile
        history (the ``trace_tail_auto`` knob); ``otlp_endpoint``
        attaches a streaming OTLP/HTTP sink for retained spans."""
        from ..observability.tracing import (LatencyErrorPolicy,
                                             SpanCollector)
        if policy is None and (slow_threshold is not None
                               or slow_percentile is not None
                               or auto_threshold):
            # an omitted threshold keeps the class default (matching the
            # silo-side SiloConfig default) so one with_tracing() call
            # yields the SAME policy for client- and silo-rooted traces
            policy = LatencyErrorPolicy(
                LatencyErrorPolicy().slow_threshold
                if slow_threshold is None else slow_threshold,
                slow_percentile or 0.0, auto=auto_threshold)
        kw = {}
        if leg_ttl is not None:
            kw["leg_ttl"] = leg_ttl
        self.tracer = SpanCollector(name, sample_rate, buffer_size,
                                    tail=tail, tail_window=tail_window,
                                    policy=policy, max_pending=max_pending,
                                    **kw)
        if otlp_endpoint:
            from ..observability.export import OtlpSink
            self.tracer.sinks.append(OtlpSink(otlp_endpoint,
                                              service_name=name))
        return self.tracer

    def _mark_remote_trace(self, msg: Message) -> None:
        """Stamp the "went remote" retention hint for a traced message
        leaving this process (tail mode only): client transmits always
        cross a process/collector boundary, so the rooting collector must
        pull peer legs before export. Called by the client transmit paths
        (ClusterClient/GatewayClient); silo egress stamps the same hint in
        MessageCenter.send_message through the same shared helper."""
        mark_remote_if_traced(self.tracer, msg)

    def try_direct_interleave(self, grain_id, method_name: str,
                              args: tuple, kwargs: dict):
        """In-silo fast path for always-interleave calls to a local, valid
        activation; None when not applicable (take the messaging path).
        Overridden by InsideRuntimeClient — external clients always
        message."""
        return None

    def try_hot_invoke(self, grain_id, grain_class: type,
                       interface_name: str, method_name: str,
                       args: tuple, kwargs: dict,
                       is_read_only: bool = False):
        """Hot-lane dispatch (runtime.hotlane): inline turn for ordinary
        calls to a local, Valid, gate-admitting activation; None when any
        complication demands the full messaging path.  Overridden where a
        local catalog is reachable (InsideRuntimeClient; ClusterClient
        over the in-proc fabric)."""
        return None

    # -- to be provided by subclass -------------------------------------
    @property
    def silo_address(self) -> SiloAddress | None:  # pragma: no cover
        raise NotImplementedError

    def transmit(self, msg: Message) -> None:  # pragma: no cover
        """Hand the message to the transport/dispatch layer."""
        raise NotImplementedError

    def transmit_batch(self, msgs: list) -> None:
        """Hand a pre-built request group to the transport as ONE unit.
        Default: per-message transmit; clients with a batched fabric
        hand-off override this so the group rides one wire batch and one
        receive-side routing hop (``MessageCenter.deliver_batch``).

        Contract for overrides: a failure AFTER any message reached the
        transport must be isolated to the failed slice via
        :meth:`_fail_transmit` (never re-raised) — raising then would
        make the caller unregister callbacks for messages that were
        already delivered and will execute. Raising is only allowed
        while provably nothing has been handed off (e.g. no gateways at
        all)."""
        for m in msgs:
            try:
                self.transmit(m)
            except Exception as e:  # noqa: BLE001 — scoped to this item
                self._fail_transmit([m], e)

    def _fail_transmit(self, msgs: list, exc: Exception) -> None:
        """Per-item transport-failure isolation for batched sends: fail
        (and unregister) exactly the messages that did NOT reach the
        transport, so already-delivered members of the same call_batch
        group complete normally. One-way messages carry no callback —
        dropped with a log, the per-message one-way contract."""
        for m in msgs:
            cb = self.callbacks.pop(m.id, None)
            if cb is not None:
                _resolve_future(cb.future, None, exc)
                # terminal before any response can correlate: the shell
                # returns to the freelist; the request message does NOT
                # (nothing proves no transport frame still holds it)
                _recycle_callback(cb)
            else:
                log.warning("batched one-way %s.%s dropped: %s",
                            m.interface_name, m.method_name, exc)

    # -- deliberate client-side batching ---------------------------------
    def call_batch(self, grain_class: type, method_name: str,
                   calls, *, timeout: float | None = None) -> list:
        """Send N ``(key, kwargs)`` invocations of ONE (class, method) as
        a deliberately-filled batch: the messages are built in one pass
        (one clock read, one call-chain/context export) and handed to the
        transport as a unit, so they ride one wire batch
        (``encode_message_batch``) and land receive-side as one routing
        hop — device-tier calls coalesce straight into a grouped
        ``VectorRuntime.call_group`` enqueue instead of relying on the
        sender's greedy drain to happen to group them.

        Returns a list of awaitables index-aligned with ``calls`` (None
        per item when the method is ``@one_way``). Per-item errors
        resolve that item's awaitable only.

        Scope: plain data-parallel payloads. When outgoing filters, a
        tracer, or ambient transaction baggage are active the batch falls
        back to N ordinary ``send_request`` calls — identical semantics,
        no batched hand-off — so interception and trace/txn propagation
        are never bypassed. Cancellation-token arguments are not
        registered on the batched path."""
        from .grain import grain_type_of, remote_methods
        fn = remote_methods(grain_class).get(method_name)
        if fn is None:
            raise AttributeError(
                f"{grain_class.__name__} has no remote method "
                f"{method_name!r}")
        read_only = getattr(fn, "__orleans_read_only__", False)
        one_way = getattr(fn, "__orleans_one_way__", False)
        interleave = getattr(fn, "__orleans_always_interleave__", False)
        gtype = grain_type_of(grain_class)
        iface = grain_class.__name__
        if (self.outgoing_call_filters or self.tracer is not None
                or RequestContext.get(TXN_KEY) is not None):
            return [self.send_request(
                target_grain=GrainId.for_grain(gtype, key),
                grain_class=grain_class, interface_name=iface,
                method_name=method_name, args=(), kwargs=kwargs,
                is_read_only=read_only, is_always_interleave=interleave,
                is_one_way=one_way, timeout=timeout)
                for key, kwargs in calls]
        timeout = self.response_timeout if timeout is None else timeout
        deadline = (time.monotonic() + timeout) if timeout else None
        sender = current_activation.get()
        chain = build_call_chain(sender)
        req_ctx = RequestContext.export()
        version = getattr(grain_class, "__orleans_version__", 0)
        send_silo = self.silo_address
        s_grain = sender.grain_id if sender else None
        s_act = sender.activation_id if sender else None
        direction = Direction.ONE_WAY if one_way else Direction.REQUEST
        loop = None if one_way else asyncio.get_running_loop()
        msgs: list[Message] = []
        out: list = []
        for key, kwargs in calls:
            msg = make_request_fast(
                Category.APPLICATION, direction, send_silo,
                s_grain, s_act, None, GrainId.for_grain(gtype, key),
                iface, method_name, copy_call_body((), kwargs),
                deadline, chain, read_only, interleave, req_ctx, version)
            msgs.append(msg)
            if one_way:
                out.append(None)
            else:
                fut = loop.create_future()
                self.callbacks[msg.id] = _fresh_callback(
                    msg, fut, deadline, None)
                out.append(fut)
        if not one_way:
            self._ensure_sweeper()
        try:
            self.transmit_batch(msgs)
        except BaseException:
            # transmit_batch's contract: it only raises while provably
            # NOTHING was handed off (partial failures are isolated
            # per-slice via _fail_transmit and not re-raised), so
            # unregistering every callback here is safe
            for m in msgs:
                self.callbacks.pop(m.id, None)
            raise
        return out

    # -- bulk-population collectives (MapReduce over actors) -------------
    _bulk_seq = 0

    def _bulk_request(self, grain_class: type, bulk_method: str,
                      spec: dict, timeout: float | None = None):
        """One APPLICATION request carrying a whole population-wide
        collective: the receiving silo anchors it (dispatcher
        ``BULK_METHODS``) — fan-out to peers, device-tier execution, and
        the combine all happen silo-side, so the CLIENT side of a
        million-actor operation is exactly one envelope + one response.
        The anchor key is SALTED per request: any silo can anchor by
        design, and a constant key would hash every bulk op for a class
        onto one gateway — concentrating the partition/combine work on
        one silo while the rest idle."""
        from .grain import grain_type_of
        self._bulk_seq += 1
        gid = GrainId.for_grain(grain_type_of(grain_class),
                                f"__bulk__{self._bulk_seq}")
        return self.send_request(
            target_grain=gid, grain_class=grain_class,
            interface_name=grain_class.__name__, method_name=bulk_method,
            args=(), kwargs={"spec": spec}, timeout=timeout)

    async def map_actors(self, grain_class: type, method: str,
                         kwargs: dict | None = None, keys=None,
                         timeout: float | None = None) -> int:
        """Apply one device-tier method (one broadcast kwargs row) to
        every live activation of ``grain_class`` — or an explicit key
        subset — as single-dispatch bulk ticks. Returns the number of
        activations applied across the cluster."""
        spec: dict = {"method": method, "kwargs": kwargs or {}}
        if keys is not None:
            spec["keys"] = list(keys) if not hasattr(keys, "tolist") \
                else keys
        if timeout is not None:
            spec["timeout"] = timeout  # anchor extends it to peer legs
        return await self._bulk_request(grain_class, "__bulk_map__",
                                        spec, timeout)

    async def reduce_actors(self, grain_class: type, method: str,
                            kwargs: dict | None = None, keys=None,
                            combine: str = "sum",
                            timeout: float | None = None):
        """Run a device-tier method over the population and reduce the
        per-actor results on device + across silos: ONE row crosses each
        host boundary (and each silo boundary) instead of N responses.
        ``combine``: "sum" | "max" | "min" | "mean". Returns the reduced
        result pytree (None when no live actor matched)."""
        spec: dict = {"method": method, "kwargs": kwargs or {},
                      "combine": combine}
        if keys is not None:
            spec["keys"] = list(keys) if not hasattr(keys, "tolist") \
                else keys
        if timeout is not None:
            spec["timeout"] = timeout
        r = await self._bulk_request(grain_class, "__bulk_reduce__",
                                     spec, timeout)
        return r["value"]

    async def broadcast_actors(self, grain_class: type, method: str,
                               targets, args: dict | None = None,
                               timeout: float | None = None) -> int:
        """Edge-list fan-out: deliver ``method`` to ``targets[i]`` with
        per-edge payload ``args[f][i]`` (scalars broadcast) — the
        celebrity-post multicast as ONE client envelope, partitioned by
        the anchor silo into one envelope per owning silo and scattered
        into target rows as device collectives. Returns edges
        delivered."""
        spec: dict = {"method": method, "targets": targets,
                      "args": args or {}}
        if timeout is not None:
            spec["timeout"] = timeout
        return await self._bulk_request(grain_class, "__bulk_broadcast__",
                                        spec, timeout)

    # server-armed join lease: the anchor polls locally this long per
    # watch envelope. Capped WELL under the 30s response timeout so a
    # watch answer (met or honest expiry) always beats the RPC deadline
    _JOIN_LEASE = 10.0

    async def join_when(self, grain_class: type, keys, k: int | None = None,
                        *, method: str, kwargs: dict | None = None,
                        timeout: float | None = None,
                        poll: float = 0.02, server: bool = True) -> int:
        """Readiness-mask join (join-calculus style): resolve when at
        least ``k`` of ``keys`` (default: all) report ready through
        ``method`` — a read-only actor method returning 0/1.

        Default (``server=True``): the client registers a readiness
        WATCH — one ``__bulk_join__`` envelope arms the anchor's poll
        reduction for a lease and the answer comes back once (met, or
        an honest lease expiry the client re-arms after). A K-poll wait
        costs ceil(wait/lease) client envelopes instead of K — the
        long-poll of the ROADMAP carry-over. ``server=False`` restores
        the per-poll client loop (one reduce_actors envelope per poll).
        Returns the ready count."""
        keys = list(keys)
        need = len(keys) if k is None else int(k)
        if not server:
            # the poll driver is the engine's (ONE readiness semantics
            # for both surfaces); imported lazily — only vector-facing
            # callers pull the dispatch/jax stack into a client process
            from ..dispatch.engine import join_poll
            return await join_poll(
                lambda: self.reduce_actors(grain_class, method, kwargs,
                                           keys=keys, combine="sum"),
                need, timeout, poll)
        loop = asyncio.get_running_loop()
        deadline = None if timeout is None else loop.time() + timeout
        ready = 0
        while True:
            remaining = None if deadline is None \
                else deadline - loop.time()
            if remaining is not None and remaining <= 0:
                raise asyncio.TimeoutError(
                    f"join_when: {ready}/{need} ready after {timeout}s")
            lease = self._JOIN_LEASE if remaining is None \
                else max(0.05, min(self._JOIN_LEASE, remaining))
            spec: dict = {"method": method, "kwargs": kwargs or {},
                          "keys": keys, "need": need, "poll": poll,
                          "lease": lease}
            r = await self._bulk_request(grain_class, "__bulk_join__",
                                         spec, timeout=lease + 15.0)
            ready = int(r.get("ready", 0))
            if r.get("met"):
                return ready

    # -- request path (SendRequest) --------------------------------------
    def send_request(self, *, target_grain: GrainId, grain_class: type,
                     interface_name: str, method_name: str,
                     args: tuple, kwargs: dict,
                     is_read_only: bool = False,
                     is_always_interleave: bool = False,
                     is_one_way: bool = False,
                     timeout: float | None = None,
                     target_silo: SiloAddress | None = None,
                     category=None):
        # filters wrap APPLICATION grain calls only: system/ping traffic
        # (membership probes, directory RPCs) must not be interceptable —
        # a user short-circuit filter would otherwise fail probes and get
        # healthy silos declared dead
        if self.outgoing_call_filters and (
                category is None or category == Category.APPLICATION):
            from .filters import OutgoingCallContext, run_call_chain

            # copy-isolate NOW, in the caller's turn: the chain runs in a
            # later task, and caller mutations between send and task start
            # must not leak into the callee (the same invariant the
            # unfiltered path gets from deep_copy at make_request time)
            args, kwargs = deep_copy((args, kwargs))

            async def terminal(c):
                res = self._send_request_unfiltered(
                    target_grain=target_grain, grain_class=grain_class,
                    interface_name=c.interface_name,
                    method_name=c.method_name,
                    args=tuple(c.args), kwargs=dict(c.kwargs),
                    is_read_only=is_read_only,
                    is_always_interleave=is_always_interleave,
                    is_one_way=is_one_way, timeout=timeout,
                    target_silo=target_silo, category=category,
                    body_precopied=True)
                return None if res is None else await res

            ctx = OutgoingCallContext(
                list(self.outgoing_call_filters), terminal,
                grain_class=grain_class, target_grain=target_grain,
                interface_name=interface_name, method_name=method_name,
                args=args, kwargs=kwargs)

            async def bounded_chain():
                # the whole chain — filters AND the call they wrap — runs
                # under the response timeout: a stalled filter must fail
                # like a stalled silo would, not wedge the caller's turn
                budget = self.response_timeout if timeout is None else timeout
                try:
                    return await asyncio.wait_for(
                        run_call_chain(ctx), budget or None)
                except asyncio.TimeoutError:
                    raise GrainCallTimeoutError(
                        f"{interface_name}.{method_name} outgoing filter "
                        f"chain timed out after {budget}s") from None

            # the task copies the caller's context NOW, so the sender
            # activation / RequestContext seen inside the chain (and by
            # the eventual unfiltered send) is the caller's
            task = asyncio.ensure_future(bounded_chain())
            if not is_one_way:
                return task
            # fire-and-forget: retain the task (weakly-held loop refs) and
            # surface filter errors in the log — there is no caller future
            self._filter_tasks.add(task)

            def _done(t: asyncio.Task) -> None:
                self._filter_tasks.discard(t)
                if not t.cancelled() and t.exception() is not None:
                    log.error("outgoing filter chain failed for one-way "
                              "%s.%s", interface_name, method_name,
                              exc_info=t.exception())

            task.add_done_callback(_done)
            return None
        return self._send_request_unfiltered(
            target_grain=target_grain, grain_class=grain_class,
            interface_name=interface_name, method_name=method_name,
            args=args, kwargs=kwargs, is_read_only=is_read_only,
            is_always_interleave=is_always_interleave,
            is_one_way=is_one_way, timeout=timeout,
            target_silo=target_silo, category=category)

    def _send_request_unfiltered(self, *, target_grain: GrainId,
                                 grain_class: type,
                                 interface_name: str, method_name: str,
                                 args: tuple, kwargs: dict,
                                 is_read_only: bool = False,
                                 is_always_interleave: bool = False,
                                 is_one_way: bool = False,
                                 timeout: float | None = None,
                                 target_silo: SiloAddress | None = None,
                                 category=None,
                                 body_precopied: bool = False):
        timeout = self.response_timeout if timeout is None else timeout
        sender = current_activation.get()
        call_chain: tuple[GrainId, ...] = build_call_chain(sender)
        # record call targets on any cancellation-token argument so
        # source.cancel() can reach remote twins (the reference's
        # _targetGrainReferences bookkeeping)
        register_outgoing_tokens(self, target_grain, grain_class,
                                 args, kwargs)
        # client span (the ActivityId-correlation upgrade): the ROOT of a
        # trace rolls head-based sampling here; unsampled calls carry no
        # header and pay only this None/ContextVar check. SYSTEM traffic
        # never roots a trace (membership probes would spam the buffer)
        # but joins an ambient sampled one — so a traced app call's
        # directory RPC shows up as a child "directory" span.
        req_ctx = RequestContext.export()
        span = None
        tracer = self.tracer
        if tracer is not None:
            tctx = current_trace.get()
            if tctx is not None:
                trace_id, parent_id = tctx
            elif (category is None or category == Category.APPLICATION) \
                    and tracer.consume_head_roll():
                # consume_head_roll honors a die already rolled by the hot
                # lane this synchronous step (the lane falls back to this
                # path on the sampled minority), else rolls here
                trace_id, parent_id = tracer.new_trace_id(), None
            else:
                trace_id = None
            if trace_id is not None:
                span = tracer.open(
                    f"{interface_name}.{method_name}",
                    "directory" if interface_name == "DirectoryTarget"
                    else "client",
                    trace_id, parent_id)
                if parent_id is None:
                    # fresh root: timer/reminder/stream-triggered work
                    # carries its ARMING context as a span link, so the
                    # new trace shows causality to the trace that armed
                    # it without the two merging
                    link = pending_root_link.get()
                    if link is not None:
                        span.links = [tuple(link)]
                req_ctx = dict(req_ctx) if req_ctx else {}
                req_ctx[TRACE_KEY] = (trace_id, span.span_id, span.start)
        # One clock read serves both the caller-side callback deadline and
        # the server-side expiry stamp (the message previously stamped its
        # own — a second monotonic read per call, ~2% in the r5
        # attribution). Server-side expiry semantics are unchanged: a
        # request that outlives its timeout while queued is still dropped
        # by the dispatcher, preserving the at-most-once story for
        # timed-out-and-retried callers.
        deadline = (time.monotonic() + timeout) if timeout else None
        # Copy-isolate arguments at send time (SerializationManager.DeepCopy
        # for in-silo calls): caller mutations after the call cannot leak into
        # the callee. Immutable-wrapped args pass by reference.
        msg = make_request_fast(
            category if category is not None else Category.APPLICATION,
            Direction.ONE_WAY if is_one_way else Direction.REQUEST,
            self.silo_address,
            sender.grain_id if sender else None,
            sender.activation_id if sender else None,
            target_silo, target_grain, interface_name, method_name,
            # filtered sends already copy-isolated at send_request time;
            # copying twice would double serialization on the hot path
            (args, kwargs) if body_precopied
            else copy_call_body(args, kwargs),
            deadline,
            call_chain, is_read_only, is_always_interleave,
            req_ctx,
            getattr(grain_class, "__orleans_version__", 0),
        )
        if span is None:
            return self._send(msg, is_one_way, deadline)
        # addressing work triggered inside transmit (directory lookups,
        # placement) runs in tasks that copy the context NOW — parent them
        # under this call's span, then restore the caller's ambient trace
        token = current_trace.set((span.trace_id, span.span_id))
        try:
            res = self._send(msg, is_one_way, deadline, span)
        except BaseException as e:
            tracer.close(span, error=type(e).__name__)
            raise
        finally:
            current_trace.reset(token)
        if res is None:  # one-way: the span covers the local send only
            tracer.close(span, one_way=True)
            return None
        return _finish_span_after(tracer, span, res)

    def _send(self, msg: Message, is_one_way: bool,
              deadline: float | None, span=None):
        if is_one_way:
            self.transmit(msg)
            return None
        loop = asyncio.get_running_loop()
        future: asyncio.Future = loop.create_future()
        self.callbacks[msg.id] = _fresh_callback(
            msg, future, deadline, RequestContext.get(TXN_KEY), span)
        self._ensure_sweeper()
        try:
            self.transmit(msg)
        except BaseException:
            self.callbacks.pop(msg.id, None)
            raise
        return self._await_response(future)

    async def _await_response(self, future: asyncio.Future):
        """Await the response with a once-per-RPC fairness yield.

        Responses resolve synchronously (receive_response), so with inline
        delivery + eager turns a whole RPC can complete before the caller
        first awaits and an await on a done future never suspends — tight
        call loops would then starve every background task (membership
        refresh, reminder ticks). Yielding here when the future is already
        done guarantees each RPC crosses the event loop exactly once, like
        a real wire hop — and exactly once, not twice, which is what the
        previous call_soon-deferred resolution cost (resolve callback +
        waiter wakeup were two separate loop iterations per call)."""
        if future.done():
            await asyncio.sleep(0)
            # non-blocking by construction: the done() check above ran
            # before the only await, and a done future cannot un-done
            return future.result()  # otpu: ignore[OTPU002]
        return await future

    # -- response path (ReceiveResponse:569-627) --------------------------
    def receive_response(self, msg: Message) -> None:
        cb = self.callbacks.pop(msg.id, None)
        if cb is None:
            log.debug("dropping late/unknown response %s", msg.id)
            # a late response's envelope is dead on arrival (its request's
            # entry already timed out/broke); the request shell itself is
            # NOT recycled on those paths — its turn may still be running
            recycle_message(msg)
            return
        if cb.future.done():
            # timed out / broken while in flight: the caller is gone and
            # this response envelope is dead on arrival — same recycle
            # rationale as the late/unknown path above. The REQUEST shell
            # stays out of the pool (its turn may still be running).
            _recycle_callback(cb)
            recycle_message(msg)
            return
        if _msg_mod._DEBUG_POOL and cb.gen is not None:
            # pool poisoning: the request shell registered with this
            # callback must not have been recycled (and possibly handed to
            # another call) while the RPC was outstanding — the dynamic
            # twin of OTPU001's static proof
            _msg_mod.assert_generation(cb.message, cb.gen,
                                       "RuntimeClient.receive_response")
        if self.tracer is not None and msg.request_context is not None:
            # response-leg network span: the server stamped the response
            # header at send (dispatcher._run_turn) — without this the
            # breakdown only sees the request leg and return-path latency
            # hides in the client-span remainder. Parented under the
            # server turn span (the sending side), like the request leg
            # parents under the client span.
            hdr = context_from_headers(msg.request_context)
            if hdr is not None:
                self.tracer.record(hdr[0], hdr[1], "network", "network",
                                   hdr[2], time.time() - hdr[2],
                                   leg="response")
        # fold callee transaction joins back into the caller's ambient
        # info (the TransactionInfo response-header merge; idempotent for
        # the in-proc shared-object case)
        if cb.txn_info is not None and msg.transaction_info is not None:
            tid, participants = msg.transaction_info
            if tid == cb.txn_info.id:
                cb.txn_info.merge(participants)
        if msg.response_kind == ResponseKind.SUCCESS:
            # synchronous resolve: the once-per-RPC fairness yield lives in
            # _await_response, so resolution itself need not burn an extra
            # event-loop iteration per call
            _resolve_future(cb.future, msg.body, None)
            # settled for good: both envelopes and the callback entry are
            # provably dereferenced now — the ONLY frames still holding the
            # request are synchronous callers up-stack (the in-proc server's
            # _run_turn finally block), which finish their reads before any
            # pool re-acquire can run on this event loop
            request = cb.message
            _recycle_callback(cb)
            recycle_message(request)
            recycle_message(msg)
        elif msg.response_kind == ResponseKind.ERROR:
            exc = msg.body if isinstance(msg.body, BaseException) else \
                RejectionError(str(msg.body))
            _resolve_future(cb.future, None, exc)
            request = cb.message
            _recycle_callback(cb)
            recycle_message(request)
            recycle_message(msg)
        else:  # rejection — transparently resend transient rejections
            # GATEWAY_TOO_BUSY is retryable: the resend re-picks a gateway
            # (the reference's client reroutes around overloaded gateways)
            if cb.span is not None and msg.rejection_type is not None:
                # span event on the still-open client invoke span: the
                # rejection (and any resend below) is part of THIS call's
                # story — without it the retry backoff reads as opaque
                # client-span time and tail-retained slow traces can't
                # show why they were slow
                cb.span.add_event(
                    "rejected", rejection=msg.rejection_type.name,
                    info=msg.rejection_info or "",
                    resend_count=cb.message.resend_count)
            if (msg.rejection_type is not None
                    and cb.message.target_grain is not None
                    and cb.message.target_grain.is_system_target()):
                # system targets are silo-bound by construction: when the
                # pinned silo is gone, re-addressing would place the id as
                # an ordinary grain and bounce to the forward limit —
                # break the caller instead (the reference's
                # BreakOutstandingMessagesToDeadSilo for pinned targets)
                _resolve_future(cb.future, None, SiloUnavailableError(
                    msg.rejection_info or "system target unreachable"))
                # terminal rejection: the callback entry left the registry
                # for good (popped above), so its shell and the rejection
                # envelope go back to the freelists. The REQUEST shell is
                # NOT recycled: on the in-proc path the rejecting silo's
                # _reject frames may still be up-stack holding it, and
                # rejections are rare enough that GC is fine.
                _recycle_callback(cb)
                recycle_message(msg)
                return
            if (msg.rejection_type is not None
                    and cb.message.resend_count < MAX_RESEND_COUNT
                    and msg.rejection_type.name in (
                        "TRANSIENT", "CACHE_INVALIDATION",
                        "GATEWAY_TOO_BUSY")):
                cb.message.resend_count += 1
                cb.message.target_silo = None  # re-address from scratch
                cb.message.target_activation = None
                self.callbacks[msg.id] = cb
                if cb.span is not None:
                    cb.span.add_event(
                        "resend", rejection=msg.rejection_type.name,
                        resend_count=cb.message.resend_count)
                # back off before re-addressing: transient rejections during
                # silo death need the directory/membership view a moment to
                # converge before the retry can land elsewhere. Jittered —
                # a shed burst retried on a synchronized schedule arrives as
                # the same burst and sheds again (thundering herd).
                delay = 0.05 * (2 ** cb.message.resend_count) * \
                    (0.5 + random.random())

                def _resend(mid=msg.id, m=cb.message):
                    if mid in self.callbacks:
                        if self.tracer is not None:
                            # the retry is a fresh hop: clear the arrival
                            # stamp and refresh the header's sent_at NOW
                            # (post-backoff) so the receiver's queue/
                            # network spans exclude the backoff — the
                            # client span still covers the whole call
                            from ..observability.tracing import \
                                restamp_header
                            m.received_at = None
                            m.request_context = restamp_header(
                                m.request_context)
                        self.transmit(m)

                asyncio.get_running_loop().call_later(delay, _resend)
                # the rejection envelope is dead once its fields were read
                # above (_resend closes over cb.message, not msg): under
                # rejection-retry storms this is the envelope churn the
                # freelist exists for
                recycle_message(msg)
                return
            if msg.rejection_type is not None and \
                    msg.rejection_type.name == "GATEWAY_TOO_BUSY":
                from ..core.errors import GatewayTooBusyError
                _resolve_future(cb.future, None, GatewayTooBusyError(
                    msg.rejection_info or "gateway overloaded"))
                _recycle_callback(cb)   # terminal: see system-target note
                recycle_message(msg)
                return
            _resolve_future(cb.future, None,
                            RejectionError(msg.rejection_info or "rejected"))
            _recycle_callback(cb)       # terminal: see system-target note
            recycle_message(msg)

    def deliver_batch(self, msgs: list) -> None:
        """Batched inbound delivery for clients (the gateway pump and the
        in-proc fabric hand one decoded/delivered group here): contiguous
        RESPONSE runs correlate via :meth:`receive_response_batch`, and
        anything else (observer notifications) takes the subclass's
        per-message ``deliver`` in arrival order. Only meaningful on
        client subclasses that define ``deliver``."""
        if not self.batched_egress:
            for m in msgs:
                self.deliver(m)  # type: ignore[attr-defined]
            return
        run: list | None = None
        for m in msgs:
            if m.direction == Direction.RESPONSE:
                if run is None:
                    run = []
                run.append(m)
                continue
            if run:
                self.receive_response_batch(run)
                run = None
            self.deliver(m)  # type: ignore[attr-defined]
        if run:
            self.receive_response_batch(run)

    def receive_response_batch(self, msgs: list) -> None:
        """Batched response correlation — the client-side leg of batched
        egress: N ``CallbackData`` lookups resolve in one pass and the
        common SUCCESS/ERROR terminals defer their freelist releases into
        ONE sweep per batch (request shell + response envelope each
        released exactly once, after every future has resolved), instead
        of per-message dict/recycle churn. Rejections (resend backoff,
        terminal-rejection bookkeeping) delegate to
        :meth:`receive_response`, which preserves their exact
        per-message semantics."""
        callbacks = self.callbacks
        tracer = self.tracer
        dead: list[Message] = []          # envelopes settled for good
        shells: list[CallbackData] = []   # callback shells to release
        for msg in msgs:
            kind = msg.response_kind
            if kind is not ResponseKind.SUCCESS and \
                    kind is not ResponseKind.ERROR:
                self.receive_response(msg)  # rejection machinery: rare
                continue
            cb = callbacks.pop(msg.id, None)
            if cb is None:
                log.debug("dropping late/unknown response %s", msg.id)
                # dead on arrival (see receive_response: the request
                # shell stays out — its turn may still be running)
                dead.append(msg)
                continue
            if cb.future.done():
                # timed out / broken while in flight: the envelope is
                # dead on arrival, the request shell stays out
                shells.append(cb)
                dead.append(msg)
                continue
            if _msg_mod._DEBUG_POOL and cb.gen is not None:
                _msg_mod.assert_generation(
                    cb.message, cb.gen,
                    "RuntimeClient.receive_response_batch")
            if tracer is not None and msg.request_context is not None:
                # response-leg network span: identical to the
                # per-message path — the server's send-side wall stamp
                # (_stamp_response) rides the batched wire unchanged
                hdr = context_from_headers(msg.request_context)
                if hdr is not None:
                    tracer.record(hdr[0], hdr[1], "network", "network",
                                  hdr[2], time.time() - hdr[2],
                                  leg="response")
            if cb.txn_info is not None and msg.transaction_info is not None:
                tid, participants = msg.transaction_info
                if tid == cb.txn_info.id:
                    cb.txn_info.merge(participants)
            if kind is ResponseKind.SUCCESS:
                _resolve_future(cb.future, msg.body, None)
            else:
                exc = msg.body if isinstance(msg.body, BaseException) else \
                    RejectionError(str(msg.body))
                _resolve_future(cb.future, None, exc)
            # settled for good: same safety argument as receive_response
            # (waiter wakeups are call_soon-deferred, so only synchronous
            # callers up-stack still hold these and they finish their
            # reads before any pool re-acquire runs on this loop)
            dead.append(cb.message)
            dead.append(msg)
            shells.append(cb)
        if dead:
            recycle_messages(dead)
        for cb in shells:
            _recycle_callback(cb)

    def break_outstanding_to_dead_silo(self, silo: SiloAddress) -> None:
        """``BreakOutstandingMessagesToDeadSilo:726``."""
        for mid, cb in list(self.callbacks.items()):
            if cb.message.target_silo is not None and \
                    cb.message.target_silo.same_endpoint(silo):
                self.callbacks.pop(mid, None)
                if not cb.future.done():
                    cb.future.set_exception(SiloUnavailableError(
                        f"silo {silo} declared dead with request in flight"))
                    # suppress "exception never retrieved" if nobody awaits
                    cb.future.exception()
                # the request envelope is NOT recycled: a dead-silo verdict
                # says nothing about whether its turn still runs somewhere
                _recycle_callback(cb)

    # -- timeout sweep (CallbackData timer analog) -------------------------
    def _ensure_sweeper(self) -> None:
        if self._timeout_sweeper is None or self._timeout_sweeper.done():
            self._timeout_sweeper = asyncio.get_running_loop().create_task(
                self._sweep_timeouts())

    async def _sweep_timeouts(self) -> None:
        while self.callbacks:
            await asyncio.sleep(0.05)
            now = time.monotonic()
            for mid, cb in list(self.callbacks.items()):
                if cb.deadline is not None and now > cb.deadline:
                    self.callbacks.pop(mid, None)
                    if not cb.future.done():
                        cb.future.set_exception(GrainCallTimeoutError(
                            f"{cb.message.interface_name}.{cb.message.method_name} "
                            f"to {cb.message.target_grain} timed out"))
                    # request envelope NOT recycled: its turn may still be
                    # running server-side (in-proc it is the same object)
                    _recycle_callback(cb)
        self._timeout_sweeper = None

    def close(self) -> None:
        for cb in self.callbacks.values():
            if not cb.future.done():
                cb.future.set_exception(SiloUnavailableError("client closed"))
                cb.future.exception()  # mark retrieved; close is best-effort
            _recycle_callback(cb)
        self.callbacks.clear()
        if self._timeout_sweeper is not None:
            self._timeout_sweeper.cancel()
            self._timeout_sweeper = None
